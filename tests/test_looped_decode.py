"""Host-free decode loop: N ragged steps fused into ONE dispatch.

The LoopedRaggedStep path (fused.LoopedRaggedStep +
model.ragged_loop_fn + engine._dispatch_loop): an in-trace
lax.while_loop runs up to N ragged decode iterations — on-device
sampling (counter-based RNG), on-device stop-token AND stop-sequence
matching, per-row done masks with early exit — and the host fetches
ONE [S, N+K+6] block of token ids + metadata per N steps instead of
one sync per token.

Acceptance oracles (all CPU, conftest forces the backend):

1. TOKEN IDENTITY vs the N=1 per-step path (and the legacy eager
   oracle): greedy and seeded stochastic, stop tokens and multi-token
   stop sequences, forced preemption, ngram speculation inside the
   loop, int8 pools, both pool layouts, and the forced 4-device CPU
   mesh.  Identical means identical — token ids AND finish reasons.
2. SAMPLER PARITY: sample_tokens_device is row-for-row identical to
   the host sample_tokens_batch across the greedy/temperature/top-k/
   top-p menu, on the SAME (seed, counter) streams — the in-trace
   twin consumes the key sequence the host path consumes, so a
   sequence can cross between paths mid-stream.
3. DISPATCH ACCOUNTING: a decode-only loop boundary is exactly 1
   dispatch and 1 host fetch for up to N tokens per row —
   generation.decode_host_fetches_per_token <= 1/N on a decode-only
   run, with loop_steps stamped and early-exit/wasted-step counters
   schema-present from build time.
"""
import importlib.util
import os

import numpy as np
import pytest

from paddle_tpu import generation as gen
from paddle_tpu.generation import metrics as gmetrics
from paddle_tpu.generation.decode_attention import ragged_paged_attention
from paddle_tpu.generation.sampling import (SampleStream, hash_uniform,
                                            sample_tokens_batch,
                                            sample_tokens_device)
from paddle_tpu.generation.speculation import NgramProposer
from paddle_tpu.profiler.monitor import StatRegistry

from gen_oracle import greedy_oracle as _ref  # noqa: E402 cross-module memo


@pytest.fixture(autouse=True)
def _fresh_generation_stats():
    reg = StatRegistry.instance()
    for name in list(reg.stats()):
        if name.startswith(gmetrics.PREFIX):
            reg.get_stat(name).reset()
    yield


@pytest.fixture(scope="module")
def model():
    # the ragged/chunked suites' signature: the process-wide greedy
    # oracle memo (gen_oracle) is shared across files
    return gen.TinyCausalLM(vocab_size=48, num_layers=2, num_heads=2,
                            head_dim=8, seed=3)


PROMPTS = [[1, 2, 3], [7, 5], [9, 9, 9, 4, 2], [11]]


def _run(model, loop_steps, *, prompts=None, max_new=10, slots=4,
         pages=128, page_size=4, chunk=3, sampling_fn=None, stop_fn=None,
         step_mode="ragged", **kw):
    """One engine run: [(token_ids, finish_reason)] + a stat snapshot
    taken before shutdown (the loop gauges are stamped per engine)."""
    cfg_kw = dict(max_decode_slots=slots, num_pages=pages,
                  page_size=page_size, prefill_chunk_tokens=chunk,
                  kv_backend="device", **kw)
    if step_mode is not None:
        cfg_kw["step_mode"] = step_mode
        cfg_kw["loop_steps"] = loop_steps
    eng = gen.GenerationEngine(model, gen.GenerationConfig(**cfg_kw),
                               start=False)
    hs = []
    for i, p in enumerate(prompts or PROMPTS):
        s = sampling_fn(i) if sampling_fn else gen.SamplingParams()
        st = stop_fn(i) if stop_fn else ()
        hs.append(eng.submit(p, max_new_tokens=max_new, sampling=s,
                             stop_tokens=st))
    eng.run_until_idle()
    out = [(h.result(timeout=5).token_ids, h.result(timeout=5)
            .finish_reason) for h in hs]
    reg = StatRegistry.instance()
    snap = {n: reg.get_stat(n).get() for n in reg.stats()
            if n.startswith(gmetrics.PREFIX)}
    assert eng.cache.utilization() == 0.0
    eng.shutdown()
    return out, snap


# ----------------------- sampler parity (oracle 2) -----------------------


def test_hash_uniform_numpy_jnp_bit_exact():
    """The counter-based RNG is BIT-exact between host and device: the
    entire parity story reduces to uint32 ops wrapping identically."""
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    seeds = rng.integers(0, 2**32, size=256, dtype=np.uint32)
    counters = rng.integers(0, 2**32, size=256, dtype=np.uint32)
    host = hash_uniform(seeds, counters)
    dev = np.asarray(hash_uniform(jnp.asarray(seeds.astype(np.int32)),
                                  jnp.asarray(counters.astype(np.int32)),
                                  jnp))
    assert host.dtype == np.float32 and dev.dtype == np.float32
    assert np.array_equal(host, dev)
    assert np.all((host >= 0.0) & (host < 1.0))


_SAMPLER_MENU = [
    gen.SamplingParams(),                                      # greedy
    gen.SamplingParams(temperature=0.7, seed=11),
    gen.SamplingParams(temperature=1.3, top_k=5, seed=12),
    gen.SamplingParams(temperature=0.9, top_p=0.8, seed=13),
    gen.SamplingParams(temperature=1.0, top_k=9, top_p=0.6, seed=14),
    gen.SamplingParams(temperature=2.5, top_k=1, seed=15),     # k=1
    gen.SamplingParams(temperature=0.4, top_p=0.999, seed=16),
]


def test_device_sampler_row_parity_menu():
    """sample_tokens_device == sample_tokens_batch row for row across
    the greedy/temperature/top-k/top-p menu and many draws — same
    tokens, same counter advancement (satellite: parity proven, not
    assumed)."""
    rng = np.random.default_rng(3)
    params = _SAMPLER_MENU
    host_rngs = [SampleStream(p.seed or 0) for p in params]
    dev_seeds = np.array([p.seed or 0 for p in params], np.int32)
    dev_counters = np.zeros(len(params), np.int32)
    for _ in range(24):
        logits = rng.standard_normal((len(params), 48)) \
            .astype(np.float32) * 3.0
        host_tokens = sample_tokens_batch(logits, params, host_rngs)
        dev_tokens, dev_counters = sample_tokens_device(
            logits, np.array([p.temperature for p in params], np.float32),
            np.array([p.top_k or 0 for p in params], np.int32),
            np.array([p.top_p if p.top_p is not None else 1.0
                      for p in params], np.float32),
            dev_seeds, dev_counters)
        dev_counters = np.asarray(dev_counters)
        assert [int(t) for t in np.asarray(dev_tokens)] == host_tokens
        assert [int(c) for c in dev_counters] \
            == [r.counter for r in host_rngs]
    # stochastic rows consumed one draw per step, greedy rows none
    assert host_rngs[0].counter == 0
    assert all(r.counter == 24 for r in host_rngs[1:])


def test_device_sampler_stream_crossing():
    """A stream sampled host -> device -> host keeps one key sequence:
    the device returns the advanced counter and the host continues it,
    identically to a pure-host run."""
    p = gen.SamplingParams(temperature=0.8, top_k=12, seed=99)
    rng = np.random.default_rng(5)
    blocks = [rng.standard_normal((1, 32)).astype(np.float32)
              for _ in range(9)]
    pure = SampleStream(99)
    want = [sample_tokens_batch(b, [p], [pure])[0] for b in blocks]
    mixed = SampleStream(99)
    got = []
    for i, b in enumerate(blocks):
        if i % 3 == 1:      # every third draw runs in-trace
            toks, ctr = sample_tokens_device(
                b, np.array([p.temperature], np.float32),
                np.array([p.top_k], np.int32), np.array([1.0], np.float32),
                np.array([p.seed], np.int32),
                np.array([mixed.counter], np.int32))
            got.append(int(np.asarray(toks)[0]))
            mixed.counter = int(np.asarray(ctr)[0]) & 0xFFFFFFFF
        else:
            got.append(sample_tokens_batch(b, [p], [mixed])[0])
    assert got == want and mixed.counter == pure.counter == 9


# ------------------- incremental ngram index (satellite) -----------------


def test_ngram_index_fuzz_matches_rescan():
    """The incremental index IS the rescan, token for token: fuzzed
    over random repetitive histories x (max_ngram, lookback) shapes."""
    rng = np.random.default_rng(11)
    for trial in range(40):
        prop = NgramProposer(max_ngram=int(rng.integers(1, 4)),
                             min_ngram=1,
                             max_lookback=int(rng.integers(6, 40)))
        # small vocab + pasted repeats: collisions and real matches
        hist = [int(t) for t in rng.integers(0, 5, size=rng.integers(2, 60))]
        if len(hist) > 8 and rng.random() < 0.7:
            hist = hist + hist[2:7]
        for k in (1, 3, 5):
            assert prop.propose(hist, k) == prop._propose_rescan(hist, k), \
                (trial, prop.max_ngram, prop.max_lookback, k, hist)


def test_ngram_propose_for_catch_up_and_retain():
    """propose_for's persistent index catches up append-only histories
    and stays token-identical to the one-shot propose; retain evicts
    finished sequences (and a shrunken history rebuilds, defensively)."""
    prop = NgramProposer(max_ngram=3, min_ngram=1, max_lookback=64)
    rng = np.random.default_rng(13)
    hist = [int(t) for t in rng.integers(0, 6, size=10)]
    for _ in range(30):
        hist.append(int(rng.integers(0, 6)))
        assert prop.propose_for("s0", hist, 4) == prop.propose(hist, 4)
    assert "s0" in prop._indexes
    prop.retain(["s1"])
    assert "s0" not in prop._indexes
    # defensive: a shorter history than indexed rebuilds from scratch
    prop.propose_for("s2", hist, 4)
    short = hist[:5]
    assert prop.propose_for("s2", short, 4) == prop.propose(short, 4)


# ------------------- loop vs per-step token identity ---------------------


@pytest.mark.parametrize("chunk", [2, 3])
def test_loop_greedy_token_identical(model, chunk):
    """Oracle 1 (greedy): loop_steps=4 == loop_steps=1 == the eager
    oracle, across prefill chunk sizes (the loop only ever takes
    decode-only boundaries; chunk steps still interleave)."""
    a, _ = _run(model, 4, chunk=chunk, max_new=12)
    b, _ = _run(model, 1, chunk=chunk, max_new=12)
    assert a == b
    for (ids, reason), p in zip(a, PROMPTS):
        assert ids == _ref(model, p, 12)
        assert reason == "length"


def test_loop_stochastic_mix_identical(model):
    """Oracle 1 (stochastic): a mixed greedy/temperature/top-k/top-p
    batch is token-identical at N=4 vs N=1 — the device sampler
    consumes the same counter-based streams the host sampler does."""
    def samp(i):
        if i % 2 == 0:
            return gen.SamplingParams()
        return gen.SamplingParams(temperature=0.9, top_k=10, top_p=0.9,
                                  seed=41 + i)

    a, _ = _run(model, 4, sampling_fn=samp, max_new=12)
    b, _ = _run(model, 1, sampling_fn=samp, max_new=12)
    assert a == b
    assert a[0][0] == _ref(model, PROMPTS[0], 12)   # greedy row unchanged


def test_loop_stop_tokens_and_sequences_identical(model):
    """Oracle 1 (stops): on-device stop-id AND multi-token stop-
    sequence matching — same clipped streams, same 'stop' reasons,
    mid-loop early exit included."""
    base, _ = _run(model, 1, max_new=12)

    def stop_fn(i):
        seq = base[i][0]
        return (seq[3],) if i == 0 and len(seq) > 3 else ()

    def samp(i):
        seq = base[i][0]
        if i == 1 and len(seq) > 4:
            # completes mid-loop: the final token must be withheld
            return gen.SamplingParams(stop_sequences=((seq[3], seq[4]),))
        return gen.SamplingParams()

    a, snap = _run(model, 4, max_new=12, sampling_fn=samp, stop_fn=stop_fn)
    b, _ = _run(model, 1, max_new=12, sampling_fn=samp, stop_fn=stop_fn)
    assert a == b
    assert a[0][1] == "stop" and a[1][1] == "stop"
    # the stop id itself is not streamed: clipped at FIRST occurrence
    assert len(a[0][0]) == base[0][0].index(base[0][0][3])
    assert snap[gmetrics.LOOP_EARLY_EXITS] >= 1


def test_loop_preemption_identical(model):
    """Oracle 1 (preemption): a pool sized to thrash — the loop's
    reserve-ahead rolls back on page shortfall and the boundary falls
    through to the single-step path, which preempts; tokens still
    match the oracle and the pool drains to empty."""
    a, _ = _run(model, 4, pages=9, chunk=2, max_new=12)
    for (ids, _), p in zip(a, PROMPTS):
        assert ids == _ref(model, p, 12)


def test_loop_speculation_identical(model):
    """Oracle 1 (speculation): ngram drafts verified INSIDE the loop
    (iteration 0) — token-identical to N=1 spec and to the no-spec
    legacy oracle, with real acceptances observed."""
    rep = [[5, 6, 9, 1, 5, 6], [4, 4, 4, 4, 4], [1, 2, 3, 1, 2, 3],
           [7, 7, 7, 2, 7, 7]]
    a, snap = _run(model, 4, prompts=rep, spec_mode="ngram", spec_tokens=3)
    b, _ = _run(model, 1, prompts=rep, spec_mode="ngram", spec_tokens=3)
    c, _ = _run(model, 1, prompts=rep, step_mode=None, chunk=0)
    assert a == b
    assert [t for t, _ in a] == [t for t, _ in c]
    assert snap[gmetrics.SPEC_PROPOSED_TOKENS] > 0
    assert snap[gmetrics.SPEC_ACCEPTED_TOKENS] > 0


def test_loop_int8_pools_identical(model):
    """int8 KV pools through the loop: lossy vs fp32, but strictly
    token-identical between N=4 and N=1 at the same storage."""
    a, _ = _run(model, 4, kv_dtype="int8", max_new=10)
    b, _ = _run(model, 1, kv_dtype="int8", max_new=10)
    assert a == b


@pytest.mark.parametrize("layout", ["token", "kernel"])
def test_loop_pool_layouts_identical(model, layout):
    """Both DeviceKVPool storage layouts carried through the loop body
    on the donation chain: token identity vs the oracle."""
    a, _ = _run(model, 4, pool_layout=layout)
    for (ids, _), p in zip(a, PROMPTS):
        assert ids == _ref(model, p, 10)


def test_loop_late_join_identical(model):
    """A fifth prompt joins mid-stream: admissions happen at loop
    boundaries, and the joined row's stream matches N=1 exactly."""
    prompts = PROMPTS + [[2, 4, 6, 8]]
    a, _ = _run(model, 4, prompts=prompts, max_new=8)
    b, _ = _run(model, 1, prompts=prompts, max_new=8)
    assert a == b


def test_loop_mesh_token_identical():
    """The loop under a head-sharded 4-device CPU mesh: one GSPMD
    dispatch per boundary, token-identical to the unsharded N=1 run,
    with collective traffic accounted per loop iteration."""
    import jax

    from paddle_tpu.parallel import tp_mesh

    assert len(jax.devices()) >= 4, "conftest forces 8 host devices"
    mesh_model = gen.TinyCausalLM(vocab_size=48, num_layers=2,
                                  num_heads=4, head_dim=8, seed=3)

    def samp(i):
        return (gen.SamplingParams() if i % 2 else
                gen.SamplingParams(temperature=0.8, top_k=8, seed=11 + i))

    a, snap = _run(mesh_model, 4, mesh=tp_mesh(4), sampling_fn=samp)
    b, _ = _run(mesh_model, 1, sampling_fn=samp)
    assert a == b
    assert snap[gmetrics.MESH_DEVICES] == 4
    assert snap[gmetrics.COLLECTIVE_BYTES_PER_STEP] > 0


def test_loop_max_new_tokens_edges(model):
    """Budgets below/at/straddling N: rows that cannot take a full loop
    still finish with the right lengths and reasons at N=4 == N=1."""
    for max_new in (1, 2, 4, 5):
        a, _ = _run(model, 4, max_new=max_new)
        b, _ = _run(model, 1, max_new=max_new)
        assert a == b, max_new
        assert all(len(ids) == max_new and r == "length"
                   for ids, r in a), max_new


# ----------------------- dispatch/fetch accounting -----------------------


def test_loop_fetch_accounting(model):
    """Acceptance: a loop boundary is ONE dispatch + ONE host fetch for
    up to N tokens per row — decode_host_fetches_per_token <= 1/N on a
    decode-only run, loop_steps stamped, early-exit/wasted counters
    schema-present from build."""
    n = 4
    a, snap = _run(model, n, max_new=12)
    assert snap[gmetrics.LOOP_STEPS] == n
    fpt = snap[gmetrics.DECODE_HOST_FETCHES_PER_TOKEN]
    assert 0 < fpt <= 1.0 / n + 0.05, fpt
    assert snap[gmetrics.DECODE_DISPATCHES_PER_STEP] == 1
    assert snap[gmetrics.DECODE_HOST_SYNCS_PER_STEP] <= 1
    # schema-complete: the loop counters exist even when they are zero
    assert gmetrics.LOOP_EARLY_EXITS in snap
    assert gmetrics.LOOP_WASTED_STEPS in snap
    # the N=1 engine stamps loop_steps=1 and never touches the ratio
    _, snap1 = _run(model, 1, max_new=12)
    assert snap1[gmetrics.LOOP_STEPS] == 1
    assert snap1[gmetrics.DECODE_HOST_FETCHES_PER_TOKEN] == 0.0


def test_loop_wasted_steps_accounting(model):
    """A row finishing mid-loop with no live peers left strands the
    remaining iterations: wasted steps are counted, not hidden."""
    base, _ = _run(model, 1, prompts=[PROMPTS[0]], max_new=12)

    def stop_fn(i):
        return (base[0][0][5],)     # stops at token 6 of 12

    a, snap = _run(model, 4, prompts=[PROMPTS[0]], max_new=12,
                   stop_fn=stop_fn)
    b, _ = _run(model, 1, prompts=[PROMPTS[0]], max_new=12,
                stop_fn=stop_fn)
    assert a == b and a[0][1] == "stop"
    assert snap[gmetrics.LOOP_EARLY_EXITS] >= 1


def test_loop_prewarm_compiles_without_dispatch(model):
    """LoopedRaggedStep.prewarm AOT-compiles the pages-bucket loop
    executable without dispatching; traffic then adds zero compiles."""
    eng = gen.GenerationEngine(model, gen.GenerationConfig(
        max_decode_slots=4, num_pages=128, page_size=4,
        prefill_chunk_tokens=3, kv_backend="device", step_mode="ragged",
        loop_steps=4), start=False)
    lp = eng._loop
    assert lp is not None
    assert lp.prewarm(2) is True
    assert lp.prewarm(2) is False          # cached
    # the longest prompt peaks in the next pages bucket (reserve-ahead
    # rows span prompt + budget + N positions)
    assert lp.prewarm(4) is True
    before = lp.compile_count
    hs = [eng.submit(p, max_new_tokens=6) for p in PROMPTS]
    eng.run_until_idle()
    for h in hs:
        h.result(timeout=5)
    assert lp.compile_count == before
    eng.shutdown()


# --------------------------- config policy -------------------------------


def test_loop_config_validation(model):
    with pytest.raises(ValueError, match="loop_steps"):
        gen.GenerationConfig(loop_steps=0)
    with pytest.raises(ValueError, match="host-free decode loop"):
        gen.GenerationConfig(step_mode="legacy", loop_steps=4)
    # loop_steps > 1 with step_mode unset auto-selects ragged
    eng = gen.GenerationEngine(model, gen.GenerationConfig(
        kv_backend="device", loop_steps=4), start=False)
    assert eng.step_mode == "ragged" and eng._loop is not None
    assert eng.loop_steps == 4
    eng.shutdown()
    # N=1 builds no loop step: the tier-1 per-step path is untouched
    eng = gen.GenerationEngine(model, gen.GenerationConfig(
        kv_backend="device", step_mode="ragged"), start=False)
    assert eng._loop is None and eng.loop_steps == 1
    eng.shutdown()

    class NoLoop:
        num_layers, num_heads, head_dim, vocab_size = 1, 1, 4, 8

        def prefill(self, tokens):
            raise NotImplementedError

        def decode(self, tokens, positions, attend):
            raise NotImplementedError

        def ragged_step_fn(self, *a, **kw):
            raise NotImplementedError

        def decode_params(self):
            raise NotImplementedError

    with pytest.raises(ValueError, match="ragged_loop_fn"):
        gen.GenerationEngine(NoLoop(), gen.GenerationConfig(
            step_mode="ragged", kv_backend="device", loop_steps=4),
            start=False)


def test_loop_oversize_stops_fall_back(model):
    """A request whose stop shapes exceed the loop executable's static
    caps makes its boundary fall back to the per-step path — correct
    output, no recompile storm."""
    lots = tuple(range(100, 112))  # 12 stop ids > max_stop_ids=8,
    # all outside the vocab so none can fire
    a, snap = _run(model, 4, prompts=[PROMPTS[0]], max_new=8,
                   stop_fn=lambda i: lots)
    b, _ = _run(model, 1, prompts=[PROMPTS[0]], max_new=8,
                stop_fn=lambda i: lots)
    assert a == b
    assert a[0][0] == _ref(model, PROMPTS[0], 8)   # none of them fire


# ------------------- gen_bench loop satellite ----------------------------


def _load_gen_bench():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "gen_bench.py")
    spec = importlib.util.spec_from_file_location("gen_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_gen_bench_loop_tokens_per_s_improves_with_n():
    """The acceptance A/B on the CPU smoke cell: fusing N steps into
    one dispatch strictly improves decode tokens/s over the per-step
    baseline (the host round-trip per token IS the CPU bottleneck),
    at one dispatch per boundary and <= 1/N host fetches per token."""
    gb = _load_gen_bench()
    bench_model = gen.TinyCausalLM(vocab_size=64, num_layers=2,
                                   num_heads=2, head_dim=8,
                                   max_positions=256, seed=0)
    cells = {n: gb.bench_loop(bench_model, batch=4, context=8,
                              new_tokens=48, page_size=4, loop_steps=n)
             for n in (1, 4)}
    assert cells[4]["tokens_per_s"] > cells[1]["tokens_per_s"], cells
    assert cells[4]["dispatches_per_step"] == 1
    assert 0 < cells[4]["host_fetches_per_token"] <= 1.0 / 4 + 0.05
    assert cells[1]["host_fetches_per_token"] == 0.0   # never loops
    # steady state: the measured pass compiles nothing at either N
    assert all(c["measured_compiles"] == 0 for c in cells.values())


@pytest.mark.slow
def test_gen_bench_loop_ladder_soak():
    """The full ladder (1, 4, 8) with stochastic sampling and the
    mid-stream-join TTFT probe: monotone tokens/s, bounded fetch
    ratio at every N, and a real join TTFT measurement per cell."""
    gb = _load_gen_bench()
    bench_model = gen.TinyCausalLM(vocab_size=64, num_layers=2,
                                   num_heads=2, head_dim=8,
                                   max_positions=512, seed=0)
    cells = {n: gb.bench_loop(bench_model, batch=4, context=8,
                              new_tokens=96, page_size=4, loop_steps=n,
                              stochastic=True, ttft_probe=True)
             for n in (1, 4, 8)}
    assert cells[4]["tokens_per_s"] > cells[1]["tokens_per_s"], cells
    assert cells[8]["tokens_per_s"] > cells[1]["tokens_per_s"], cells
    for n in (4, 8):
        assert 0 < cells[n]["host_fetches_per_token"] <= 1.0 / n + 0.05
        assert cells[n]["ttft_join_s"] > 0
        assert cells[n]["dispatches_per_step"] == 1


def test_ragged_descriptor_rank_guard():
    """The loop-body-safe contract: malformed descriptor ranks raise a
    named error at trace time instead of silently broadcasting."""
    pool = gen.DeviceKVPool(1, 2, 8, num_pages=8, page_size=4)
    pool.allocate("A")
    arr = np.ones((1, 4, 2, 8), np.float32)
    pool.append_prefill("A", arr, arr)
    pt, _ = pool.gather_block_tables(["A"])
    q = np.ones((2, 2, 8), np.float32)
    k_pool, v_pool = pool.layer_pools(0)
    with pytest.raises(ValueError, match=r"\[S\]-shaped"):
        ragged_paged_attention(q, k_pool, v_pool, pt,
                               np.int32(0),            # scalar start
                               np.array([1], np.int32),
                               np.array([4], np.int32))
    with pytest.raises(ValueError, match=r"\[S\]-shaped"):
        ragged_paged_attention(q, k_pool, v_pool, pt[0],   # rank-1 table
                               np.array([0], np.int32),
                               np.array([1], np.int32),
                               np.array([4], np.int32))
