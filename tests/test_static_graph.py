"""Static graph: Program IR + Executor + append_backward + optimizer bridge.

Mirrors the reference's static-path tests (SURVEY §3.1 call stack; fit-a-line
style book test).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static


def test_program_build_and_run():
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 3])
        y = static.nn.fc(x, 2)
        out = static.nn.relu(y)
    exe = static.Executor()
    exe.run(startup)
    res = exe.run(main, feed={"x": np.ones((4, 3), np.float32)},
                  fetch_list=[out])
    assert res[0].shape == (4, 2)
    assert (res[0] >= 0).all()


def test_append_backward_and_sgd_converges():
    """fit-a-line: y = xw+b fitted by static SGD (book test parity)."""
    rng = np.random.RandomState(0)
    true_w = rng.rand(3, 1).astype(np.float32)
    X = rng.rand(64, 3).astype(np.float32)
    Y = X @ true_w + 0.1

    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [64, 3])
        y = static.data("y", [64, 1])
        pred = static.nn.fc(x, 1)
        diff = pred - y
        loss = static.nn.mean(diff * diff)
        opt = paddle.optimizer.SGD(learning_rate=0.5)
        opt.minimize(loss)

    exe = static.Executor()
    exe.run(startup)
    losses = []
    for _ in range(30):
        out = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        losses.append(float(out[0][0]))
    assert losses[-1] < losses[0] * 0.1, losses[::10]


def test_program_rewrite_ops_visible():
    """Meta-optimizer-style op-list assertion (the reference's key dist-test
    trick, SURVEY §4.4): check grad + update ops exist after minimize."""
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 3])
        pred = static.nn.fc(x, 1)
        loss = static.nn.mean(pred)
        opt = paddle.optimizer.Adam(learning_rate=0.1)
        opt.minimize(loss)
    types = [op.type for op in main.global_block().ops]
    assert any(t.endswith("_grad") for t in types)
    assert "adam" in types
    # grads named param@GRAD exist
    assert any(v.endswith("@GRAD") for v in main.global_block().vars)


def test_fleet_raw_program_inserts_allreduce():
    """raw_program meta-opt inserts c_allreduce_sum
    (test_fleet_*_meta_optimizer parity)."""
    from paddle_tpu.distributed import fleet

    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 3])
        pred = static.nn.fc(x, 1)
        loss = static.nn.mean(pred)
        strategy = fleet.DistributedStrategy()
        strategy.without_graph_optimization = True
        fleet.init(is_collective=True, strategy=strategy)
        opt = fleet.distributed_optimizer(
            paddle.optimizer.SGD(learning_rate=0.1), strategy=strategy)
        fleet.fleet.minimize(loss)
    types = [op.type for op in main.global_block().ops]
    assert "c_allreduce_sum" in types


def test_fleet_amp_meta_optimizer_ops():
    from paddle_tpu.distributed import fleet

    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 3])
        pred = static.nn.fc(x, 1)
        loss = static.nn.mean(pred)
        strategy = fleet.DistributedStrategy()
        strategy.amp = True
        fleet.init(is_collective=True, strategy=strategy)
        opt = fleet.distributed_optimizer(
            paddle.optimizer.SGD(learning_rate=0.1), strategy=strategy)
        fleet.fleet.minimize(loss)
    types = [op.type for op in main.global_block().ops]
    assert "check_finite_and_unscale" in types
    assert "update_loss_scaling" in types


def test_fleet_sharding_meta_optimizer_ops():
    from paddle_tpu.distributed import fleet

    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 3])
        pred = static.nn.fc(x, 4)
        pred2 = static.nn.fc(pred, 1)
        loss = static.nn.mean(pred2)
        strategy = fleet.DistributedStrategy()
        strategy.sharding = True
        strategy.sharding_configs = {"sharding_degree": 4}
        fleet.init(is_collective=True, strategy=strategy)
        opt = fleet.distributed_optimizer(
            paddle.optimizer.Momentum(learning_rate=0.1), strategy=strategy)
        fleet.fleet.minimize(loss)
    types = [op.type for op in main.global_block().ops]
    assert "c_broadcast" in types
    assert "c_reduce_sum" in types


def test_static_save_load_roundtrip(tmp_path):
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 3])
        pred = static.nn.fc(x, 2)
    exe = static.Executor()
    exe.run(startup)
    feed = {"x": np.ones((2, 3), np.float32)}
    before = exe.run(main, feed=feed, fetch_list=[pred])[0]
    path = str(tmp_path / "model")
    static.save(main, path)

    # zero the scope params, reload, outputs must be restored
    from paddle_tpu.static.executor import global_scope
    import jax.numpy as jnp

    scope = global_scope()
    for v in main.list_vars():
        if v.persistable and scope.get(v.name) is not None:
            scope.set(v.name, jnp.zeros_like(scope.get(v.name)))
    static.load(main, path)
    after = exe.run(main, feed=feed, fetch_list=[pred])[0]
    np.testing.assert_allclose(before, after, rtol=1e-6)


def test_static_batch_norm_updates_running_stats():
    """Training-mode static BN must blend batch stats into the running
    Mean/Variance vars in place (batch_norm_op.cc:396-398) so a trained
    program serves correctly with is_test=True."""
    import paddle_tpu as paddle

    paddle.seed(0)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [8, 3, 4, 4])
        y = static.nn.batch_norm(x, momentum=0.9)
        out = static.nn.mean(y)
    exe = static.Executor()
    scope = static.Scope()
    exe.run(startup, scope=scope)
    mean_name = next(n for n in scope.names() if "bn_mean" in n)
    var_name = next(n for n in scope.names() if "bn_var" in n)
    rng = np.random.RandomState(0)
    ref_mean = np.zeros(3, np.float64)
    ref_var = np.ones(3, np.float64)
    for i in range(3):
        xv = (rng.rand(8, 3, 4, 4) * (i + 1)).astype(np.float32)
        exe.run(main, feed={"x": xv}, fetch_list=[out], scope=scope)
        bm = xv.mean(axis=(0, 2, 3))
        bv = xv.var(axis=(0, 2, 3))
        ref_mean = 0.9 * ref_mean + 0.1 * bm
        ref_var = 0.9 * ref_var + 0.1 * bv
    np.testing.assert_allclose(np.asarray(scope.get(mean_name)), ref_mean,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(scope.get(var_name)), ref_var,
                               rtol=1e-4, atol=1e-5)


def test_static_dropout_fresh_mask_each_step_and_deterministic():
    """The compile-once trap: a fixed PRNG key would reuse ONE mask for
    every executed step. The counter-threaded dropout draws a fresh mask
    per run, reproducibly across fresh scopes, and the inference pass
    still strips it."""
    import paddle_tpu as paddle
    from paddle_tpu.static.passes import get_pass

    paddle.seed(0)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 64])
        y = static.nn.dropout(x, dropout_prob=0.5)
    exe = static.Executor()

    def masks(scope):
        exe.run(startup, scope=scope)
        xv = np.ones((4, 64), np.float32)
        return [np.asarray(exe.run(main, feed={"x": xv}, fetch_list=[y],
                                   scope=scope)[0]) for _ in range(2)]

    m1, m2 = masks(static.Scope())
    assert not np.array_equal(m1, m2)          # fresh mask per step
    r1, _ = masks(static.Scope())
    np.testing.assert_array_equal(m1, r1)      # deterministic sequence

    infer = main.clone() if hasattr(main, "clone") else main
    get_pass("delete_dropout_inference").apply(infer)
    scope = static.Scope()
    exe.run(startup, scope=scope)
    out = exe.run(infer, feed={"x": np.ones((4, 64), np.float32)},
                  fetch_list=[y], scope=scope)[0]
    np.testing.assert_array_equal(out, np.ones((4, 64), np.float32))


def test_static_dropout_backward_uses_forward_mask():
    """The step counter is executor-advanced (constant within a run), so
    the vjp grad replay reconstructs the EXACT forward mask — an in-place
    increment would hand backward a different mask (silent corruption)."""
    import paddle_tpu as paddle

    paddle.seed(3)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 8])
        h = static.nn.fc(x, 16, bias_attr=False)
        y = static.nn.dropout(h, dropout_prob=0.5)
        loss = static.nn.mean(y)
        static.append_backward(loss)
    exe = static.Executor()
    scope = static.Scope()
    exe.run(startup, scope=scope)
    w_name = next(n for n in scope.names() if n.startswith("param"))
    rng = np.random.RandomState(0)
    xv = rng.rand(4, 8).astype(np.float32)
    yv, gw = exe.run(main, feed={"x": xv},
                     fetch_list=[y, w_name + "@GRAD"], scope=scope)
    mask = (np.asarray(yv) != 0).astype(np.float64)
    want = xv.T @ (mask / 0.5) / mask.size
    np.testing.assert_allclose(np.asarray(gw), want, rtol=1e-4, atol=1e-6)
    # and clone(for_test=True) really disables the mask (closure strip)
    infer = main.clone(for_test=True)
    out = exe.run(infer, feed={"x": xv}, fetch_list=[y], scope=scope)[0]
    assert (np.asarray(out) != 0).all()
