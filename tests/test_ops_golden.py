"""Op-level golden tests over the OpTest harness (SURVEY §4 tier 1).

Covers the priority op set from SURVEY §7.4 (reduce_sum, elementwise family,
matmul, conv2d, pool2d, softmax, layer_norm, batch_norm, embedding, dropout,
cross entropy) with numeric-gradient checks.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import OpTest


class TestMatmul(OpTest):
    op = staticmethod(paddle.matmul)

    def make_inputs(self):
        rng = np.random.RandomState(1)
        return [rng.rand(3, 4).astype(np.float32),
                rng.rand(4, 5).astype(np.float32)]

    def ref(self, x, y):
        return x @ y

    def test(self):
        self.check_output()
        self.check_grad(wrt=(0, 1))


class TestMatmulTranspose(OpTest):
    op = staticmethod(paddle.matmul)
    attrs = {"transpose_y": True}

    def make_inputs(self):
        rng = np.random.RandomState(2)
        return [rng.rand(3, 4).astype(np.float32),
                rng.rand(5, 4).astype(np.float32)]

    def ref(self, x, y):
        return x @ y.T

    def test(self):
        self.check_output()
        self.check_grad(wrt=(0, 1))


class TestElementwiseAdd(OpTest):
    op = staticmethod(paddle.add)

    def make_inputs(self):
        rng = np.random.RandomState(3)
        return [rng.rand(4, 5).astype(np.float32),
                rng.rand(5).astype(np.float32)]  # broadcast

    def ref(self, x, y):
        return x + y

    def test(self):
        self.check_output()
        self.check_grad(wrt=(0, 1))


class TestElementwiseMul(OpTest):
    op = staticmethod(paddle.multiply)

    def make_inputs(self):
        rng = np.random.RandomState(4)
        return [rng.rand(4, 5).astype(np.float32),
                rng.rand(4, 5).astype(np.float32)]

    def ref(self, x, y):
        return x * y

    def test(self):
        self.check_output()
        self.check_grad(wrt=(0, 1))


class TestReduceSum(OpTest):
    op = staticmethod(paddle.sum)
    attrs = {"axis": 1}

    def make_inputs(self):
        return [np.random.RandomState(5).rand(3, 7).astype(np.float32)]

    def ref(self, x):
        return x.sum(1)

    def test(self):
        self.check_output()
        self.check_grad()


class TestMean(OpTest):
    op = staticmethod(paddle.mean)

    def make_inputs(self):
        return [np.random.RandomState(6).rand(3, 7).astype(np.float32)]

    def ref(self, x):
        return np.mean(x)

    def test(self):
        self.check_output()
        self.check_grad()


class TestSoftmax(OpTest):
    op = staticmethod(F.softmax)

    def make_inputs(self):
        return [np.random.RandomState(7).rand(4, 10).astype(np.float32)]

    def ref(self, x):
        e = np.exp(x - x.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    def test(self):
        self.check_output()
        self.check_grad()


class TestConv2D(OpTest):
    op = staticmethod(F.conv2d)
    attrs = {"stride": 1, "padding": 1}
    out_atol = 1e-4

    def make_inputs(self):
        rng = np.random.RandomState(8)
        return [rng.rand(2, 3, 8, 8).astype(np.float32),
                rng.rand(4, 3, 3, 3).astype(np.float32)]

    def ref(self, x, w):
        import torch
        import torch.nn.functional as TF

        return TF.conv2d(torch.tensor(x), torch.tensor(w), padding=1).numpy()

    def test(self):
        self.check_output()
        self.check_grad(wrt=(1,), delta=1e-2)


class TestMaxPool2D(OpTest):
    op = staticmethod(F.max_pool2d)
    attrs = {"kernel_size": 2, "stride": 2}

    def make_inputs(self):
        return [np.random.RandomState(9).rand(2, 3, 8, 8).astype(np.float32)]

    def ref(self, x):
        import torch
        import torch.nn.functional as TF

        return TF.max_pool2d(torch.tensor(x), 2, 2).numpy()

    def test(self):
        self.check_output()


class TestAvgPool2D(OpTest):
    op = staticmethod(F.avg_pool2d)
    attrs = {"kernel_size": 2, "stride": 2}

    def make_inputs(self):
        return [np.random.RandomState(10).rand(2, 3, 8, 8).astype(np.float32)]

    def ref(self, x):
        import torch
        import torch.nn.functional as TF

        return TF.avg_pool2d(torch.tensor(x), 2, 2).numpy()

    def test(self):
        self.check_output()
        self.check_grad()


class TestLayerNorm(OpTest):
    @staticmethod
    def op(x, w, b):
        return F.layer_norm(x, [8], weight=w, bias=b)

    out_atol = 1e-5

    def make_inputs(self):
        rng = np.random.RandomState(11)
        return [rng.rand(4, 8).astype(np.float32),
                rng.rand(8).astype(np.float32),
                rng.rand(8).astype(np.float32)]

    def ref(self, x, w, b):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) / np.sqrt(var + 1e-5) * w + b

    def test(self):
        self.check_output()
        self.check_grad(wrt=(0, 1, 2))


class TestBatchNormInfer(OpTest):
    @staticmethod
    def op(x, m, v, w, b):
        return F.batch_norm(x, m, v, weight=w, bias=b, training=False)

    def make_inputs(self):
        rng = np.random.RandomState(12)
        return [rng.rand(4, 3, 5, 5).astype(np.float32),
                rng.rand(3).astype(np.float32),
                (rng.rand(3) + 0.5).astype(np.float32),
                rng.rand(3).astype(np.float32),
                rng.rand(3).astype(np.float32)]

    def ref(self, x, m, v, w, b):
        sh = (1, 3, 1, 1)
        return (x - m.reshape(sh)) / np.sqrt(v.reshape(sh) + 1e-5) * \
            w.reshape(sh) + b.reshape(sh)

    def test(self):
        self.check_output()


class TestEmbedding(OpTest):
    @staticmethod
    def op(w):
        ids = paddle.to_tensor(np.array([[0, 2], [1, 3]], np.int32))
        return F.embedding(ids, w)

    def make_inputs(self):
        return [np.random.RandomState(13).rand(5, 4).astype(np.float32)]

    def ref(self, w):
        return w[np.array([[0, 2], [1, 3]])]

    def test(self):
        self.check_output()
        self.check_grad()


class TestSoftmaxWithCE(OpTest):
    @staticmethod
    def op(logits):
        lbl = paddle.to_tensor(np.array([[1], [3], [0]], np.int64))
        return F.softmax_with_cross_entropy(logits, lbl)

    def make_inputs(self):
        return [np.random.RandomState(14).rand(3, 5).astype(np.float32)]

    def ref(self, x):
        e = np.exp(x - x.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        lbl = np.array([1, 3, 0])
        return -np.log(p[np.arange(3), lbl])[:, None]

    def test(self):
        self.check_output()
        self.check_grad()


class TestGelu(OpTest):
    op = staticmethod(F.gelu)
    out_atol = 1e-5

    def make_inputs(self):
        return [np.random.RandomState(15).randn(4, 6).astype(np.float32)]

    def ref(self, x):
        from scipy.stats import norm  # noqa — fallback below if unavailable

        return x * norm.cdf(x)

    def test(self):
        try:
            self.check_output()
        except ImportError:
            pass
        self.check_grad()


class TestTranspose(OpTest):
    op = staticmethod(paddle.transpose)
    attrs = {"perm": [1, 0, 2]}

    def make_inputs(self):
        return [np.random.RandomState(16).rand(2, 3, 4).astype(np.float32)]

    def ref(self, x):
        return x.transpose(1, 0, 2)

    def test(self):
        self.check_output()
        self.check_grad()


class TestReshape(OpTest):
    op = staticmethod(paddle.reshape)
    attrs = {"shape": [6, 4]}

    def make_inputs(self):
        return [np.random.RandomState(17).rand(2, 3, 4).astype(np.float32)]

    def ref(self, x):
        return x.reshape(6, 4)

    def test(self):
        self.check_output()
        self.check_grad()


class TestConcat(OpTest):
    @staticmethod
    def op(x, y):
        return paddle.concat([x, y], axis=1)

    def make_inputs(self):
        rng = np.random.RandomState(18)
        return [rng.rand(2, 3).astype(np.float32),
                rng.rand(2, 2).astype(np.float32)]

    def ref(self, x, y):
        return np.concatenate([x, y], axis=1)

    def test(self):
        self.check_output()
        self.check_grad(wrt=(0, 1))


class TestDropoutEval(OpTest):
    @staticmethod
    def op(x):
        return F.dropout(x, p=0.5, training=False)

    def make_inputs(self):
        return [np.random.RandomState(19).rand(4, 4).astype(np.float32)]

    def ref(self, x):
        return x

    def test(self):
        self.check_output()


def test_dropout_train_statistics():
    paddle.seed(123)
    x = paddle.ones([1000])
    y = F.dropout(x, p=0.3, training=True)
    kept = float((y.numpy() > 0).mean())
    assert abs(kept - 0.7) < 0.08
    # upscale: kept values are 1/(1-p)
    vals = y.numpy()[y.numpy() > 0]
    np.testing.assert_allclose(vals, 1.0 / 0.7, rtol=1e-5)
