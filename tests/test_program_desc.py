"""Serialized ProgramDesc round-trip (framework.proto:202 parity).

A forward program serializes to JSON and rebuilds through the op-builder
registry; the rebuilt program produces identical outputs given the same
parameter values.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.static.desc import (
    desc_to_program, load_program, program_to_desc, save_program,
)


def _copy_params(src_scope, desc, dst_scope):
    for n, vd in desc["vars"].items():
        if vd["persistable"] and src_scope.get(n) is not None:
            dst_scope.set(n, src_scope.get(n))


def test_mlp_roundtrip(tmp_path):
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4, 8])
            h = static.nn.fc(x, 16)
            h = static.nn.relu(h)
            h = static.nn.dropout(h, dropout_prob=0.5, is_test=True)
            out = static.nn.softmax(static.nn.fc(h, 3))
        exe = static.Executor()
        exe.run(startup)
        xv = np.random.RandomState(0).randn(4, 8).astype("float32")
        ref = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]

        path = str(tmp_path / "model.pdmodel.json")
        save_program(main, path)
        loaded = load_program(path)

        # same op list, fresh fns
        assert [op.type for op in loaded.global_block().ops] == \
            [op.type for op in main.global_block().ops]
        from paddle_tpu.static.executor import Scope

        scope = Scope()
        from paddle_tpu.static.executor import global_scope

        _copy_params(global_scope(), program_to_desc(main), scope)
        out2 = loaded.global_block().var(out.name)
        exe2 = static.Executor()
        got = exe2.run(loaded, feed={"x": xv}, fetch_list=[out2],
                       scope=scope)[0]
        np.testing.assert_allclose(got, ref, rtol=1e-6)
    finally:
        paddle.disable_static()


def test_conv_bn_pool_roundtrip(tmp_path):
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2, 3, 8, 8])
            y = static.nn.conv2d(x, 4, 3, stride=1, padding=1)
            y = static.nn.batch_norm(y, act="relu", is_test=True)
            y = static.nn.pool2d(y, pool_size=2, pool_type="max",
                                 pool_stride=2)
            y = static.nn.pool2d(y, global_pooling=True, pool_type="avg")
            out = static.nn.flatten(y, axis=1)
        exe = static.Executor()
        exe.run(startup)
        xv = np.random.RandomState(1).randn(2, 3, 8, 8).astype("float32")
        ref = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]

        desc = program_to_desc(main)
        assert all(o["rebuildable"] for o in desc["ops"]), [
            o["type"] for o in desc["ops"] if not o["rebuildable"]]
        loaded = desc_to_program(desc)
        from paddle_tpu.static.executor import Scope, global_scope

        scope = Scope()
        _copy_params(global_scope(), desc, scope)
        exe2 = static.Executor()
        got = exe2.run(loaded, feed={"x": xv},
                       fetch_list=[loaded.global_block().var(out.name)],
                       scope=scope)[0]
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    finally:
        paddle.disable_static()


def test_startup_program_roundtrip_initializes(tmp_path):
    """Startup programs rebuild their init ops from serialized
    initializer descriptors."""
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2, 4])
            out = static.nn.fc(x, 3)
        path = str(tmp_path / "startup.json")
        save_program(startup, path)
        loaded = load_program(path)
        from paddle_tpu.static.executor import Scope

        scope = Scope()
        exe = static.Executor()
        exe.run(loaded, scope=scope)
        for n in program_to_desc(startup)["vars"]:
            v = scope.get(n)
            if v is not None:
                assert np.isfinite(np.asarray(v)).all()
        # at least the fc weight materialized with the right shape
        weights = [np.asarray(scope.get(n))
                   for n, vd in program_to_desc(startup)["vars"].items()
                   if vd["is_parameter"] and len(vd["shape"]) == 2]
        assert weights and weights[0].shape == (4, 3)
    finally:
        paddle.disable_static()


def test_unknown_op_type_raises_on_load():
    from paddle_tpu.errors import UnimplementedError

    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2])
            from paddle_tpu.static.nn_static import emit

            emit("my_custom_closure_op", [("X", x)],
                 [("Out", [2], "float32")], lambda v: v * 2)
        desc = program_to_desc(main)
        # closures now serialize via embedded StableHLO; an artifact whose
        # hlo payload is absent (old/foreign producer) must still raise
        # with the builder list at load, not fail silently
        assert desc["ops"][-1]["rebuildable"] and "hlo" in desc["ops"][-1]
        desc["ops"][-1].pop("hlo")
        desc["ops"][-1]["rebuildable"] = False
        with pytest.raises(UnimplementedError, match="my_custom_closure_op"):
            desc_to_program(desc)
    finally:
        paddle.disable_static()


def test_trained_program_json_is_pruned_and_loadable(tmp_path):
    """save_inference_model after minimize: the JSON desc is the pruned
    feed->fetch forward slice and loads cleanly (review finding: the
    unpruned program carried unbuildable grad/update closures)."""
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4, 8])
            out = static.nn.fc(x, 3)
            loss = static.nn.mean(out * out)
            opt = paddle.optimizer.SGD(learning_rate=0.1)
            opt.minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        exe.run(main, feed={"x": np.ones((4, 8), np.float32)},
                fetch_list=[loss])
        prefix = str(tmp_path / "trained")
        static.save_inference_model(prefix, [x], [out], exe, program=main)
        loaded = load_program(prefix + ".pdmodel.json")
        types = [op.type for op in loaded.global_block().ops]
        assert "sgd" not in types and not any("grad" in t for t in types)
        assert "fc" in types
    finally:
        paddle.disable_static()
