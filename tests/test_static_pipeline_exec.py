"""Static pipeline EXECUTION (SectionWorker analogue).

The fleet pipeline meta-opt's stage annotations now drive real execution:
per-stage chunks jit separately and run with inputs committed to the
stage's device (the inter-stage device_put is the send_v2/recv_v2
transfer), micro-batches accumulate param grads, and the update phase
runs once per global batch on each param's owning stage.  Parity bar:
losses equal the plain single-device whole-block run, step by step.

Ref: section_worker.cc:104 (micro-batch loop), pipeline_trainer.cc
(per-stage sections), meta_optimizers/pipeline_optimizer.py:228.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.distributed.fleet import Fleet
from paddle_tpu.distributed.fleet.distributed_strategy import (
    DistributedStrategy,
)
from paddle_tpu.distributed.fleet.meta_optimizers import (
    apply_meta_optimizers,
)

STEPS = 4
RNG = np.random.RandomState(0)
XS = [RNG.rand(8, 16).astype(np.float32) for _ in range(STEPS)]
YS = [RNG.rand(8, 1).astype(np.float32) for _ in range(STEPS)]


def _build(pp_degree=None, accumulate_steps=1):
    paddle.seed(0)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [8, 16])
        y = static.data("y", [8, 1])
        h = static.nn.relu(static.nn.fc(x, 16))
        h = static.nn.relu(static.nn.fc(h, 16))
        h = static.nn.relu(static.nn.fc(h, 16))
        h = static.nn.relu(static.nn.fc(h, 16))
        out = static.nn.fc(h, 1)
        loss = static.nn.mean((out - y) * (out - y))
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        if pp_degree is None:
            opt.minimize(loss)
        else:
            strategy = DistributedStrategy()
            strategy.pipeline = True
            strategy.pipeline_configs = {
                "pp_degree": pp_degree,
                "accumulate_steps": accumulate_steps,
            }
            f = Fleet()
            f.init(is_collective=True, strategy=strategy)
            apply_meta_optimizers(opt, strategy, loss, startup, f)
    return main, startup, loss


def _train(pp_degree=None, accumulate_steps=1):
    main, startup, loss = _build(pp_degree, accumulate_steps)
    scope = static.Scope()
    exe = static.Executor()
    exe.run(startup, scope=scope)
    losses = []
    for xv, yv in zip(XS, YS):
        out = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss],
                      scope=scope)
        losses.append(float(np.asarray(out[0]).reshape(())))
    return losses, exe, scope, main


def test_static_pipeline_executes_with_loss_parity():
    base, *_ = _train()
    got, exe, scope, main = _train(pp_degree=2)
    assert main._pipeline_opt["num_stages"] == 2
    np.testing.assert_allclose(got, base, rtol=2e-5, atol=1e-6)
    # the block really ran pipelined: a PipelinedBlock served it and the
    # stages' params live on different devices
    from paddle_tpu.static.pipeline_exec import PipelinedBlock

    pbs = [cb for cb in exe._cache.values()
           if isinstance(cb, PipelinedBlock)]
    assert pbs, "executor did not route to the pipelined path"
    pb = pbs[0]
    stages = {pb.stage_of_param(n) for n in pb.param_names
              if pb.stage_of_param(n) is not None}
    assert stages == {0, 1}
    devs = {list(scope.get(n).devices())[0] for n in pb.param_names
            if hasattr(scope.get(n), "devices")}
    assert len(devs) == 2  # param storage split across stage devices


def test_static_pipeline_microbatch_grad_accumulation_parity():
    """accumulate_steps=4: micro-batch grad accumulation must equal the
    full-batch step (mean loss, equal micro sizes)."""
    base, *_ = _train()
    got, exe, _, main = _train(pp_degree=2, accumulate_steps=4)
    assert main._pipeline_opt["accumulate_steps"] == 4
    np.testing.assert_allclose(got, base, rtol=2e-5, atol=1e-6)


def test_static_pipeline_backward_ops_annotated_by_forward_stage():
    """Grad/update ops carry the stage of their forward counterpart, not
    an index-uniform split (the round-2 annotation put all backward ops
    in the last stage)."""
    main, _, _ = _build(pp_degree=2)
    block = main.global_block()
    stages = {}
    for op in block.ops:
        if op.fn is None:
            continue
        stages.setdefault(op.type, []).append(
            op.attrs.get("pipeline_stage"))
    # the first fc's update must be on stage 0, the head fc's on stage 1
    assert 0 in stages.get("momentum", []) and 1 in stages.get(
        "momentum", [])
    # grad ops span both stages too
    grad_stages = [s for t, ss in stages.items() if t.endswith("_grad")
                   for s in ss]
    assert 0 in grad_stages and 1 in grad_stages


def test_static_pipeline_batchlike_fetch_concats_scalar_averages():
    """A per-sample fetch concatenates over micro-batches; the scalar loss
    averages — classification comes from static shapes, so a micro batch
    of 1 cannot be mistaken for a scalar."""
    paddle.seed(0)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [8, 16])
        y = static.data("y", [8, 1])
        h = static.nn.relu(static.nn.fc(x, 16))
        h = static.nn.relu(static.nn.fc(h, 16))
        out = static.nn.fc(h, 1)
        loss = static.nn.mean((out - y) * (out - y))
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        strategy = DistributedStrategy()
        strategy.pipeline = True
        strategy.pipeline_configs = {"pp_degree": 2, "accumulate_steps": 8}
        f = Fleet()
        f.init(is_collective=True, strategy=strategy)
        apply_meta_optimizers(opt, strategy, loss, startup, f)
    scope = static.Scope()
    exe = static.Executor()
    exe.run(startup, scope=scope)
    preds, lv = exe.run(main, feed={"x": XS[0], "y": YS[0]},
                        fetch_list=[out, loss], scope=scope)
    assert preds.shape == (8, 1)  # concatenated, micro batch was 1
    assert np.asarray(lv).size == 1  # averaged loss view


def test_static_pipeline_1f1b_schedule_parity_and_memory_bound():
    """schedule_mode=1 (section_worker.cc:167-183): identical losses to
    F-then-B and to the single-device run, with in-flight micro-batch
    envs bounded by the stage count instead of accumulate_steps."""
    base, *_ = _train()
    paddle.seed(0)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [8, 16])
        y = static.data("y", [8, 1])
        h = static.nn.relu(static.nn.fc(x, 16))
        h = static.nn.relu(static.nn.fc(h, 16))
        h = static.nn.relu(static.nn.fc(h, 16))
        h = static.nn.relu(static.nn.fc(h, 16))
        out = static.nn.fc(h, 1)
        loss = static.nn.mean((out - y) * (out - y))
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        strategy = DistributedStrategy()
        strategy.pipeline = True
        strategy.pipeline_configs = {"pp_degree": 2, "accumulate_steps": 8,
                                     "schedule_mode": 1}
        f = Fleet()
        f.init(is_collective=True, strategy=strategy)
        apply_meta_optimizers(opt, strategy, loss, startup, f)
    assert main._pipeline_opt["schedule_mode"] == 1
    scope = static.Scope()
    exe = static.Executor()
    exe.run(startup, scope=scope)
    losses = []
    for xv, yv in zip(XS, YS):
        outv = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss],
                       scope=scope)
        losses.append(float(np.asarray(outv[0]).reshape(())))
    np.testing.assert_allclose(losses, base, rtol=2e-5, atol=1e-6)
    from paddle_tpu.static.pipeline_exec import PipelinedBlock

    pb = [c for c in exe._cache.values() if isinstance(c, PipelinedBlock)][0]
    # 8 micro-batches, 2 stages: at most 2 envs ever live under 1F1B
    assert pb.num_micro == 8
    assert pb.last_peak_live_micros == 2


def test_static_pipeline_with_batch_norm_running_stats():
    """In-place BN running stats flow through pipelined chunks (an op that
    reads AND writes the same var must still get it fed into its chunk)."""
    paddle.seed(0)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [8, 16])
        y = static.data("y", [8, 1])
        h = static.nn.relu(static.nn.fc(x, 16))
        h = static.nn.reshape(h, [-1, 16, 1, 1])
        h = static.nn.batch_norm(h, momentum=0.9)
        h = static.nn.reshape(h, [-1, 16])
        h = static.nn.relu(static.nn.fc(h, 16))
        out = static.nn.fc(h, 1)
        loss = static.nn.mean((out - y) * (out - y))
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        strategy = DistributedStrategy()
        strategy.pipeline = True
        strategy.pipeline_configs = {"pp_degree": 2, "accumulate_steps": 2}
        f = Fleet()
        f.init(is_collective=True, strategy=strategy)
        apply_meta_optimizers(opt, strategy, loss, startup, f)
    scope = static.Scope()
    exe = static.Executor()
    exe.run(startup, scope=scope)
    mean_name = next(n for n in scope.names() if "bn_mean" in n)
    before = np.asarray(scope.get(mean_name)).copy()
    for xv, yv in zip(XS[:2], YS[:2]):
        exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss],
                scope=scope)
    after = np.asarray(scope.get(mean_name))
    assert not np.allclose(before, after)  # stats really updated


def test_static_pipeline_custom_optimizer_subclass_parity():
    """static_minimize names the update op after the optimizer SUBCLASS
    ('warmupmomentum' — optimizer_bridge.py:62), which falls outside the
    UPDATE_OP_TYPES whitelist: detection must be structural (param@GRAD
    in, param out) or the update silently runs once per micro-batch on
    unaveraged grads instead of once per global batch."""

    class WarmupMomentum(paddle.optimizer.Momentum):
        pass

    def train(pp):
        paddle.seed(0)
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [8, 16])
            y = static.data("y", [8, 1])
            h = static.nn.relu(static.nn.fc(x, 16))
            h = static.nn.relu(static.nn.fc(h, 16))
            out = static.nn.fc(h, 1)
            loss = static.nn.mean((out - y) * (out - y))
            opt = WarmupMomentum(learning_rate=0.1, momentum=0.9)
            if pp is None:
                opt.minimize(loss)
            else:
                strategy = DistributedStrategy()
                strategy.pipeline = True
                strategy.pipeline_configs = {"pp_degree": pp,
                                             "accumulate_steps": 4}
                f = Fleet()
                f.init(is_collective=True, strategy=strategy)
                apply_meta_optimizers(opt, strategy, loss, startup, f)
        scope = static.Scope()
        exe = static.Executor()
        exe.run(startup, scope=scope)
        losses = [
            float(np.asarray(
                exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss],
                        scope=scope)[0]).reshape(()))
            for xv, yv in zip(XS, YS)
        ]
        return losses, exe

    base, _ = train(None)
    got, exe = train(2)
    from paddle_tpu.static.pipeline_exec import PipelinedBlock

    pb = [c for c in exe._cache.values() if isinstance(c, PipelinedBlock)][0]
    # the subclass-named ops landed in the update phase, not a chunk
    assert pb.update_ops and all(
        op.type == "warmupmomentum" for _, op in pb.update_ops)
    assert not any(op.type == "warmupmomentum"
                   for _, ops in pb.chunks for op in ops)
    np.testing.assert_allclose(got, base, rtol=2e-5, atol=1e-6)


def test_static_pipeline_bn_stats_chain_across_micros():
    """Running BN stats chain through the micro-batches of one global
    batch (M sequential section runs in the reference SectionWorker), not
    reset to the batch-start snapshot per micro: after one step with
    accumulate_steps=2 the running mean is the two-fold chained EMA."""
    paddle.seed(0)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [8, 16])
        y = static.data("y", [8, 1])
        h = static.nn.reshape(x, [-1, 16, 1, 1])
        h = static.nn.batch_norm(h, momentum=0.9)
        h = static.nn.reshape(h, [-1, 16])
        h = static.nn.relu(static.nn.fc(h, 16))
        out = static.nn.fc(h, 1)
        loss = static.nn.mean((out - y) * (out - y))
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        strategy = DistributedStrategy()
        strategy.pipeline = True
        strategy.pipeline_configs = {"pp_degree": 2, "accumulate_steps": 2}
        f = Fleet()
        f.init(is_collective=True, strategy=strategy)
        apply_meta_optimizers(opt, strategy, loss, startup, f)
    scope = static.Scope()
    exe = static.Executor()
    exe.run(startup, scope=scope)
    exe.run(main, feed={"x": XS[0], "y": YS[0]}, fetch_list=[loss],
            scope=scope)
    mean_name = next(n for n in scope.names() if "bn_mean" in n)
    got = np.asarray(scope.get(mean_name))
    # numpy oracle: BN sits on the raw feed, so per-micro batch means are
    # feature means of the micro rows; chained EMA with momentum 0.9
    m1 = 0.9 * np.zeros(16) + 0.1 * XS[0][:4].mean(axis=0)
    m2 = 0.9 * m1 + 0.1 * XS[0][4:].mean(axis=0)
    np.testing.assert_allclose(got, m2, rtol=1e-5, atol=1e-6)


def test_static_pipeline_dynamic_batch_fetch_concats():
    """With the conventional -1 batch dim on static.data, a per-sample
    fetch must still concatenate over micro-batches (shape (B, ...)), not
    element-wise average micro slices into (B/M, ...)."""
    paddle.seed(0)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [-1, 16])
        y = static.data("y", [-1, 1])
        h = static.nn.relu(static.nn.fc(x, 16))
        h = static.nn.relu(static.nn.fc(h, 16))
        out = static.nn.fc(h, 1)
        loss = static.nn.mean((out - y) * (out - y))
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        strategy = DistributedStrategy()
        strategy.pipeline = True
        strategy.pipeline_configs = {"pp_degree": 2, "accumulate_steps": 2}
        f = Fleet()
        f.init(is_collective=True, strategy=strategy)
        apply_meta_optimizers(opt, strategy, loss, startup, f)
    scope = static.Scope()
    exe = static.Executor()
    exe.run(startup, scope=scope)
    preds, lv = exe.run(main, feed={"x": XS[0], "y": YS[0]},
                        fetch_list=[out, loss], scope=scope)
    assert preds.shape == (8, 1)  # micro batch 4: concatenated, not blended
    assert np.asarray(lv).size == 1  # loss still averages


def test_static_pipeline_propagated_dyn_dim_fetch_concats():
    """Static feed batch but a reshape(-1) in the graph propagates a -1
    leading dim to the fetch var: runtime classification against the
    per-micro batch must still concatenate per-sample fetches."""
    paddle.seed(0)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [8, 16])
        y = static.data("y", [8, 1])
        h = static.nn.reshape(x, [-1, 16])
        h = static.nn.relu(static.nn.fc(h, 16))
        h = static.nn.relu(static.nn.fc(h, 16))
        out = static.nn.fc(h, 1)
        loss = static.nn.mean((out - y) * (out - y))
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        strategy = DistributedStrategy()
        strategy.pipeline = True
        strategy.pipeline_configs = {"pp_degree": 2, "accumulate_steps": 2}
        f = Fleet()
        f.init(is_collective=True, strategy=strategy)
        apply_meta_optimizers(opt, strategy, loss, startup, f)
    assert main.global_block().vars[out.name].shape[0] in (-1, None)
    scope = static.Scope()
    exe = static.Executor()
    exe.run(startup, scope=scope)
    preds, lv = exe.run(main, feed={"x": XS[0], "y": YS[0]},
                        fetch_list=[out, loss], scope=scope)
    assert preds.shape == (8, 1)
    assert np.asarray(lv).size == 1
