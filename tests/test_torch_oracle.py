"""Hard-kernel oracles from torch (CPU) — an independent reference
implementation for the kernels whose semantics are too intricate for
hand-written numpy (conv stride/pad/dilation/groups, transposed convs,
grid_sample, interpolate, ctc_loss, unpool, unfold, affine_grid).

The reference's op_test uses numpy oracles; for these kernels numpy
reimplementations would just mirror our own code, so torch's
battle-tested CPU kernels serve as the disinterested referee instead
(same NCHW conventions as the reference).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

torch = pytest.importorskip("torch")
import torch.nn.functional as TF  # noqa: E402


def _np(t):
    return np.asarray(t._data if hasattr(t, "_data") else t)


def _t(a):
    return paddle.to_tensor(a)


def _tt(a):
    return torch.from_numpy(np.asarray(a))


R = np.random.RandomState(0)


@pytest.mark.parametrize("stride,padding,dilation,groups", [
    (1, 0, 1, 1),
    (2, 1, 1, 1),
    (1, 2, 2, 1),
    (1, 1, 1, 2),
    (1, 1, 1, 4),  # depthwise (groups == channels, the MobileNet path)
])
def test_conv2d_matches_torch(stride, padding, dilation, groups):
    x = R.randn(2, 4, 9, 9).astype(np.float32)
    cout = 8 if groups == 4 else 6
    w = R.randn(cout, 4 // groups, 3, 3).astype(np.float32)
    b = R.randn(cout).astype(np.float32)
    got = _np(F.conv2d(_t(x), _t(w), _t(b), stride=stride, padding=padding,
                       dilation=dilation, groups=groups))
    want = TF.conv2d(_tt(x), _tt(w), _tt(b), stride=stride, padding=padding,
                     dilation=dilation, groups=groups).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("stride,padding", [(1, 0), (2, 1)])
def test_conv2d_transpose_matches_torch(stride, padding):
    x = R.randn(2, 4, 5, 5).astype(np.float32)
    w = R.randn(4, 3, 3, 3).astype(np.float32)  # (Cin, Cout, kh, kw)
    got = _np(F.conv2d_transpose(_t(x), _t(w), stride=stride,
                                 padding=padding))
    want = TF.conv_transpose2d(_tt(x), _tt(w), stride=stride,
                               padding=padding).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_conv3d_matches_torch():
    x = R.randn(1, 2, 5, 5, 5).astype(np.float32)
    w = R.randn(3, 2, 2, 2, 2).astype(np.float32)
    got = _np(F.conv3d(_t(x), _t(w), stride=2, padding=1))
    want = TF.conv3d(_tt(x), _tt(w), stride=2, padding=1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_conv3d_transpose_matches_torch():
    x = R.randn(1, 2, 3, 3, 3).astype(np.float32)
    w = R.randn(2, 3, 2, 2, 2).astype(np.float32)
    got = _np(F.conv3d_transpose(_t(x), _t(w), stride=2))
    want = TF.conv_transpose3d(_tt(x), _tt(w), stride=2).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("mode,align", [
    ("bilinear", True), ("bilinear", False), ("nearest", False),
])
def test_grid_sample_matches_torch(mode, align):
    x = R.randn(1, 2, 5, 5).astype(np.float32)
    grid = (R.rand(1, 4, 4, 2).astype(np.float32) * 2 - 1)
    got = _np(F.grid_sample(_t(x), _t(grid), mode=mode,
                            align_corners=align))
    want = TF.grid_sample(_tt(x), _tt(grid), mode=mode,
                          align_corners=align).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("mode,align,size", [
    ("nearest", False, (7, 7)),
    ("bilinear", False, (7, 7)),
    ("bilinear", True, (7, 7)),
    ("bicubic", False, (6, 6)),
])
def test_interpolate_matches_torch(mode, align, size):
    x = R.randn(1, 2, 4, 4).astype(np.float32)
    kw = {} if mode == "nearest" else {"align_corners": align}
    got = _np(F.interpolate(_t(x), size=list(size), mode=mode, **kw))
    want = TF.interpolate(_tt(x), size=size, mode=mode, **kw).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("align", [True, False])
def test_affine_grid_matches_torch(align):
    theta = R.randn(2, 2, 3).astype(np.float32)
    got = _np(F.affine_grid(_t(theta), [2, 1, 4, 5], align_corners=align))
    want = TF.affine_grid(_tt(theta), [2, 1, 4, 5],
                          align_corners=align).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def _log_softmax_np(logits):
    m = logits.max(-1, keepdims=True)
    z = logits - m
    return (z - np.log(np.exp(z).sum(-1, keepdims=True))).astype(np.float32)


def test_ctc_loss_matches_torch():
    T_, B, C = 6, 2, 5
    logits = R.randn(T_, B, C).astype(np.float32)
    log_probs = _log_softmax_np(logits)
    labels = np.array([[1, 2, 3], [2, 3, 4]], np.int64)
    in_len = np.array([6, 6], np.int64)
    lab_len = np.array([3, 2], np.int64)
    got = _np(F.ctc_loss(_t(log_probs.astype(np.float32)), _t(labels),
                         _t(in_len), _t(lab_len), blank=0,
                         reduction="none"))
    want = TF.ctc_loss(_tt(log_probs), _tt(labels), _tt(in_len),
                       _tt(lab_len), blank=0, reduction="none").numpy()
    np.testing.assert_allclose(np.ravel(got), np.ravel(want),
                               rtol=1e-3, atol=1e-3)


def test_max_unpool2d_matches_torch():
    x = R.randn(1, 2, 6, 6).astype(np.float32)
    tout, tidx = TF.max_pool2d(_tt(x), 2, return_indices=True)
    got = _np(paddle.max_unpool2d(_t(tout.numpy()), _t(tidx.numpy()), 2))
    want = TF.max_unpool2d(tout, tidx, 2).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pixel_shuffle_matches_torch():
    x = R.randn(1, 8, 3, 3).astype(np.float32)
    got = _np(F.pixel_shuffle(_t(x), 2))
    want = TF.pixel_shuffle(_tt(x), 2).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_unfold_matches_torch():
    x = R.randn(1, 2, 5, 5).astype(np.float32)
    got = _np(F.unfold(_t(x), [2, 2], strides=2, paddings=1))
    want = TF.unfold(_tt(x), (2, 2), stride=2, padding=1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_avg_max_pool2d_padding_matches_torch():
    x = R.randn(1, 2, 7, 7).astype(np.float32)
    # paddle avg_pool2d defaults exclusive=True (padding not counted)
    got = _np(F.avg_pool2d(_t(x), 3, stride=2, padding=1))
    want = TF.avg_pool2d(_tt(x), 3, stride=2, padding=1,
                         count_include_pad=False).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    got = _np(F.max_pool2d(_t(x), 3, stride=2, padding=1))
    want = TF.max_pool2d(_tt(x), 3, stride=2, padding=1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_log_softmax_gelu_silu_match_torch():
    x = R.randn(3, 7).astype(np.float32)
    np.testing.assert_allclose(
        _np(F.log_softmax(_t(x), axis=-1)),
        TF.log_softmax(_tt(x), dim=-1).numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        _np(F.gelu(_t(x))), TF.gelu(_tt(x)).numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        _np(F.silu(_t(x))), TF.silu(_tt(x)).numpy(), rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# gradient parity: our vjp vs torch autograd, same random cotangent


def _grad_pair(pfn, tfn, arrays, wrt):
    ts = [_t(a) for a in arrays]
    for i, v in enumerate(ts):
        v.stop_gradient = (i != wrt)
    out = pfn(*ts)
    co = np.asarray(np.random.RandomState(7).standard_normal(
        _np(out).shape), np.float32)
    (out * _t(co)).sum().backward()
    got = _np(ts[wrt].grad)

    tts = [torch.tensor(a, requires_grad=(i == wrt))
           for i, a in enumerate(arrays)]
    tout = tfn(*tts)
    (tout * _tt(co)).sum().backward()
    want = tts[wrt].grad.numpy()
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("wrt", [0, 1])
def test_conv2d_grad_matches_torch(wrt):
    x = R.randn(2, 4, 7, 7).astype(np.float32)
    w = R.randn(6, 2, 3, 3).astype(np.float32)
    _grad_pair(
        lambda xv, wv: F.conv2d(xv, wv, None, stride=1, padding=1,
                                dilation=2, groups=2),
        lambda xv, wv: TF.conv2d(xv, wv, None, stride=1, padding=1,
                                 dilation=2, groups=2),
        [x, w], wrt)


@pytest.mark.parametrize("wrt", [0, 1])
def test_conv2d_transpose_grad_matches_torch(wrt):
    x = R.randn(1, 3, 5, 5).astype(np.float32)
    w = R.randn(3, 2, 3, 3).astype(np.float32)
    _grad_pair(
        lambda xv, wv: F.conv2d_transpose(xv, wv, stride=2, padding=1),
        lambda xv, wv: TF.conv_transpose2d(xv, wv, stride=2, padding=1),
        [x, w], wrt)


@pytest.mark.parametrize("wrt", [0, 1])
def test_grid_sample_grad_matches_torch(wrt):
    x = R.randn(1, 2, 5, 5).astype(np.float32)
    grid = (R.rand(1, 3, 3, 2).astype(np.float32) * 1.6 - 0.8)
    _grad_pair(
        lambda xv, gv: F.grid_sample(xv, gv, align_corners=True),
        lambda xv, gv: TF.grid_sample(xv, gv, align_corners=True),
        [x, grid], wrt)


@pytest.mark.parametrize("mode,align", [
    ("bilinear", True), ("bilinear", False), ("bicubic", False),
])
def test_interpolate_grad_matches_torch(mode, align):
    x = R.randn(1, 2, 4, 4).astype(np.float32)
    _grad_pair(
        lambda xv: F.interpolate(xv, size=[7, 7], mode=mode,
                                 align_corners=align),
        lambda xv: TF.interpolate(xv, size=(7, 7), mode=mode,
                                  align_corners=align),
        [x], 0)


def test_ctc_loss_grad_matches_torch():
    T_, B, C = 6, 2, 5
    logits = R.randn(T_, B, C).astype(np.float32)
    lp = _log_softmax_np(logits)
    labels = np.array([[1, 2, 3], [2, 3, 4]], np.int64)
    in_len = np.array([6, 6], np.int64)
    lab_len = np.array([3, 2], np.int64)
    _grad_pair(
        lambda pv: F.ctc_loss(pv, _t(labels), _t(in_len), _t(lab_len),
                              blank=0, reduction="sum"),
        lambda pv: TF.ctc_loss(pv, _tt(labels), _tt(in_len), _tt(lab_len),
                               blank=0, reduction="sum"),
        [lp], 0)


# ---------------------------------------------------------------------------
# recurrent cells: same gate order/formulas as torch, weights copied


def test_lstm_cell_matches_torch():
    paddle.seed(0)
    cell = paddle.nn.LSTMCell(4, 3)
    tcell = torch.nn.LSTMCell(4, 3)
    with torch.no_grad():
        tcell.weight_ih.copy_(_tt(_np(cell.weight_ih)))
        tcell.weight_hh.copy_(_tt(_np(cell.weight_hh)))
        tcell.bias_ih.copy_(_tt(_np(cell.bias_ih)))
        tcell.bias_hh.copy_(_tt(_np(cell.bias_hh)))
    x = R.randn(2, 4).astype(np.float32)
    h0 = R.randn(2, 3).astype(np.float32)
    c0 = R.randn(2, 3).astype(np.float32)
    _, (h, c) = cell(_t(x), (_t(h0), _t(c0)))
    th, tc = tcell(_tt(x), (_tt(h0), _tt(c0)))
    np.testing.assert_allclose(_np(h), th.detach().numpy(), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(_np(c), tc.detach().numpy(), rtol=1e-4,
                               atol=1e-5)


def test_gru_cell_matches_torch():
    paddle.seed(0)
    cell = paddle.nn.GRUCell(4, 3)
    tcell = torch.nn.GRUCell(4, 3)
    with torch.no_grad():
        tcell.weight_ih.copy_(_tt(_np(cell.weight_ih)))
        tcell.weight_hh.copy_(_tt(_np(cell.weight_hh)))
        tcell.bias_ih.copy_(_tt(_np(cell.bias_ih)))
        tcell.bias_hh.copy_(_tt(_np(cell.bias_hh)))
    x = R.randn(2, 4).astype(np.float32)
    h0 = R.randn(2, 3).astype(np.float32)
    h, _ = cell(_t(x), _t(h0))
    th = tcell(_tt(x), _tt(h0))
    np.testing.assert_allclose(_np(h), th.detach().numpy(), rtol=1e-4,
                               atol=1e-5)


def test_bidirectional_stacked_lstm_matches_torch():
    """2-layer bidirectional LSTM over a sequence: same parameter names
    as torch (weight_ih_l{k}[_reverse] ...), weights copied directly."""
    paddle.seed(0)
    net = paddle.nn.LSTM(4, 3, num_layers=2, direction="bidirect")
    tnet = torch.nn.LSTM(4, 3, num_layers=2, bidirectional=True,
                         batch_first=True)
    params = dict(net.named_parameters())
    with torch.no_grad():
        for name, _ in tnet.named_parameters():
            getattr(tnet, name).copy_(_tt(_np(params[name])))
    x = R.randn(2, 5, 4).astype(np.float32)
    out, (h, c) = net(_t(x))
    tout, (th, tc) = tnet(_tt(x))
    np.testing.assert_allclose(_np(out), tout.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(_np(h), th.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(_np(c), tc.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_multihead_attention_matches_torch():
    """Our separate q/k/v projections vs torch's packed in_proj, weights
    mapped (paddle Linear stores [in, out] = torch weight transposed)."""
    paddle.seed(0)
    E, H, B, L = 8, 2, 2, 5
    mha = paddle.nn.MultiHeadAttention(E, H)
    tmha = torch.nn.MultiheadAttention(E, H, batch_first=True)
    qw = _np(mha.q_proj.weight).T
    kw = _np(mha.k_proj.weight).T
    vw = _np(mha.v_proj.weight).T
    qb = _np(mha.q_proj.bias)
    kb = _np(mha.k_proj.bias)
    vb = _np(mha.v_proj.bias)
    with torch.no_grad():
        tmha.in_proj_weight.copy_(_tt(np.concatenate([qw, kw, vw], 0)))
        tmha.in_proj_bias.copy_(_tt(np.concatenate([qb, kb, vb], 0)))
        tmha.out_proj.weight.copy_(_tt(_np(mha.out_proj.weight).T))
        tmha.out_proj.bias.copy_(_tt(_np(mha.out_proj.bias)))
    x = R.randn(B, L, E).astype(np.float32)
    got = _np(mha(_t(x), _t(x), _t(x)))
    want, _ = tmha(_tt(x), _tt(x), _tt(x))
    np.testing.assert_allclose(got, want.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_softmax_cross_entropy_grad_matches_torch():
    logits = R.randn(4, 6).astype(np.float32)
    labels = np.array([1, 3, 5, 0], np.int64)
    _grad_pair(
        lambda lv: F.softmax_with_cross_entropy(
            lv, _t(labels.reshape(-1, 1))).sum(),
        lambda lv: TF.cross_entropy(lv, _tt(labels), reduction="sum"),
        [logits], 0)


# ---------------------------------------------------------------------------
# optimizer trajectories: multi-step parity where paddle and torch
# semantics coincide (Adam/AdamW bias correction, SGD momentum, global-
# norm clipping). Paddle-specific rules (rmsprop eps-in-sqrt, lamb, ...)
# are validated against the reference formulas in the golden suites
# instead — torch would be the WRONG oracle there.


def _train_pair(make_opts, steps=8, clip=None):
    W0 = R.randn(4, 3).astype(np.float32)
    B0 = R.randn(3).astype(np.float32)
    X = R.randn(16, 4).astype(np.float32)
    Y = R.randn(16, 3).astype(np.float32)

    lin = paddle.nn.Linear(4, 3)
    with paddle.no_grad():
        lin.weight.set_value(W0)
        lin.bias.set_value(B0)
    tlin = torch.nn.Linear(4, 3)
    with torch.no_grad():
        tlin.weight.copy_(_tt(W0.T))
        tlin.bias.copy_(_tt(B0))
    opt, topt = make_opts(lin, tlin)
    for _ in range(steps):
        loss = ((lin(_t(X)) - _t(Y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()

        tloss = ((tlin(_tt(X)) - _tt(Y)) ** 2).mean()
        tloss.backward()
        if clip is not None:
            torch.nn.utils.clip_grad_norm_(tlin.parameters(), clip)
        topt.step()
        topt.zero_grad()
    np.testing.assert_allclose(_np(lin.weight), tlin.weight.detach().numpy().T,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(_np(lin.bias), tlin.bias.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_adam_trajectory_matches_torch():
    _train_pair(lambda l, tl: (
        paddle.optimizer.Adam(learning_rate=0.05, parameters=l.parameters(),
                              beta1=0.9, beta2=0.99, epsilon=1e-8),
        torch.optim.Adam(tl.parameters(), lr=0.05, betas=(0.9, 0.99),
                         eps=1e-8)))


def test_adamw_decoupled_decay_trajectory_matches_torch():
    _train_pair(lambda l, tl: (
        paddle.optimizer.AdamW(learning_rate=0.05,
                               parameters=l.parameters(),
                               weight_decay=0.1),
        torch.optim.AdamW(tl.parameters(), lr=0.05, weight_decay=0.1)))


def test_momentum_trajectory_matches_torch():
    _train_pair(lambda l, tl: (
        paddle.optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                                  parameters=l.parameters()),
        torch.optim.SGD(tl.parameters(), lr=0.05, momentum=0.9)))


def test_adam_with_global_norm_clip_matches_torch():
    clip = 0.05  # small enough that clipping actually engages every step
    _train_pair(lambda l, tl: (
        paddle.optimizer.Adam(
            learning_rate=0.05, parameters=l.parameters(),
            grad_clip=paddle.nn.ClipGradByGlobalNorm(clip)),
        torch.optim.Adam(tl.parameters(), lr=0.05)), clip=clip)


# ---------------------------------------------------------------------------
# attention hot path vs torch SDPA (the GPT/BERT inner loop)


@pytest.mark.parametrize("causal,use_flash", [
    (False, False), (True, False), (True, True), (False, True),
])
def test_scaled_dot_product_attention_matches_torch(causal, use_flash):
    from paddle_tpu.ops.attention import scaled_dot_product_attention

    B, H, Lq, D = 2, 2, 16, 8
    q = R.randn(B, H, Lq, D).astype(np.float32)
    k = R.randn(B, H, Lq, D).astype(np.float32)
    v = R.randn(B, H, Lq, D).astype(np.float32)
    out, _ = scaled_dot_product_attention(_t(q), _t(k), _t(v),
                                          is_causal=causal,
                                          use_flash=use_flash)
    want = TF.scaled_dot_product_attention(
        _tt(q), _tt(k), _tt(v), is_causal=causal).numpy()
    np.testing.assert_allclose(_np(out), want, rtol=1e-3, atol=2e-4)


def test_sdpa_additive_mask_matches_torch():
    from paddle_tpu.ops.attention import scaled_dot_product_attention

    B, H, L, D = 1, 2, 8, 4
    q = R.randn(B, H, L, D).astype(np.float32)
    k = R.randn(B, H, L, D).astype(np.float32)
    v = R.randn(B, H, L, D).astype(np.float32)
    mask = np.where(R.rand(1, 1, L, L) > 0.3, 0.0, -1e9).astype(np.float32)
    out, _ = scaled_dot_product_attention(_t(q), _t(k), _t(v),
                                          attn_mask=_t(mask),
                                          use_flash=False)
    want = TF.scaled_dot_product_attention(
        _tt(q), _tt(k), _tt(v), attn_mask=_tt(mask)).numpy()
    np.testing.assert_allclose(_np(out), want, rtol=1e-3, atol=2e-4)


@pytest.mark.parametrize("wrt", [0, 1, 2])
def test_sdpa_causal_grad_matches_torch(wrt):
    from paddle_tpu.ops.attention import scaled_dot_product_attention

    B, H, L, D = 1, 2, 8, 4
    arrs = [R.randn(B, H, L, D).astype(np.float32) for _ in range(3)]

    def pfn(qv, kv, vv):
        return scaled_dot_product_attention(qv, kv, vv, is_causal=True,
                                            use_flash=False)[0]

    _grad_pair(
        pfn,
        lambda qv, kv, vv: TF.scaled_dot_product_attention(
            qv, kv, vv, is_causal=True),
        arrs, wrt)


def test_flash_attention_grad_matches_plain():
    """The Pallas blockwise custom_vjp must produce the same grads as the
    straightforward softmax attention (its contract)."""
    from paddle_tpu.ops.attention import scaled_dot_product_attention

    B, H, L, D = 1, 2, 32, 8
    q = R.randn(B, H, L, D).astype(np.float32)
    k = R.randn(B, H, L, D).astype(np.float32)
    v = R.randn(B, H, L, D).astype(np.float32)
    co = R.randn(B, H, L, D).astype(np.float32)

    def run(use_flash):
        grads = {}
        ts = [_t(a) for a in (q, k, v)]
        for t_ in ts:
            t_.stop_gradient = False
        o, _ = scaled_dot_product_attention(*ts, is_causal=True,
                                            use_flash=use_flash)
        (o * _t(co)).sum().backward()
        return [_np(t_.grad) for t_ in ts]

    g_plain = run(False)
    g_flash = run(True)
    for a, b in zip(g_plain, g_flash):
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-3)


def test_batchnorm_training_running_stats_match_torch():
    """Train-mode BN: normalized outputs match torch (both normalize by
    BIASED batch stats); the running-variance buffer does NOT — torch
    blends the UNBIASED batch variance while the reference blends the
    biased one (batch_norm_op.cc:367 divides by N*sample_size, :398
    feeds it straight into the running update), so the buffers are
    checked against the reference formula instead.  paddle momentum=0.9
    corresponds to torch momentum=0.1 (opposite naming)."""
    paddle.seed(0)
    bn = paddle.nn.BatchNorm2D(3, momentum=0.9)
    tbn = torch.nn.BatchNorm2d(3, momentum=0.1)
    with torch.no_grad():
        tbn.weight.copy_(_tt(_np(bn.weight)))
        tbn.bias.copy_(_tt(_np(bn.bias)))
    bn.train()
    tbn.train()
    ref_mean = np.zeros(3, np.float64)
    ref_var = np.ones(3, np.float64)
    for i in range(3):
        x = R.randn(4, 3, 5, 5).astype(np.float32) * (i + 1) + i
        got = _np(bn(_t(x)))
        want = tbn(_tt(x)).detach().numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)
        bm = x.mean(axis=(0, 2, 3))
        bv = x.var(axis=(0, 2, 3))  # biased, reference semantics
        ref_mean = 0.9 * ref_mean + 0.1 * bm
        ref_var = 0.9 * ref_var + 0.1 * bv
    np.testing.assert_allclose(_np(bn._mean), ref_mean, rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_allclose(_np(bn._variance), ref_var, rtol=1e-3,
                               atol=1e-4)
    # eval mode applies the (reference-semantics) running stats
    bn.eval()
    x = R.randn(2, 3, 5, 5).astype(np.float32)
    want = ((x - ref_mean.reshape(1, 3, 1, 1))
            / np.sqrt(ref_var.reshape(1, 3, 1, 1) + 1e-5)
            * _np(bn.weight).reshape(1, 3, 1, 1)
            + _np(bn.bias).reshape(1, 3, 1, 1))
    np.testing.assert_allclose(_np(bn(_t(x))), want, rtol=1e-3, atol=1e-4)


def test_embedding_padding_idx_matches_torch():
    """padding_idx zeroes the output row AND its gradient contribution."""
    V, D = 10, 4
    w = R.randn(V, D).astype(np.float32)
    ids = np.array([[1, 3, 3, 0, 7]], np.int64)  # 3 is the padding idx
    _grad_pair(
        lambda wv: F.embedding(_t(ids), wv, padding_idx=3),
        lambda wv: TF.embedding(_tt(ids), wv, padding_idx=3),
        [w], 0)
    out = F.embedding(_t(ids), _t(w), padding_idx=3)
    assert np.allclose(_np(out)[0, 1], 0) and np.allclose(_np(out)[0, 2], 0)


def test_lstm_interlayer_dropout_semantics():
    """The stacked-RNN dropout arg must actually drop between layers in
    train mode (it was stored-but-ignored), stay off in eval, and leave
    single-layer nets untouched."""
    paddle.seed(0)
    net = paddle.nn.LSTM(4, 3, num_layers=2, dropout=0.5)
    x = _t(np.ones((2, 5, 4), np.float32))
    net.train()
    o1, o2 = _np(net(x)[0]), _np(net(x)[0])
    assert not np.array_equal(o1, o2)
    net.eval()
    e1, e2 = _np(net(x)[0]), _np(net(x)[0])
    np.testing.assert_array_equal(e1, e2)
    single = paddle.nn.LSTM(4, 3, num_layers=1, dropout=0.5)
    single.train()
    s1, s2 = _np(single(x)[0]), _np(single(x)[0])
    np.testing.assert_array_equal(s1, s2)


def test_lstm_sequence_length_matches_torch_packed():
    """sequence_length semantics (previously silently ignored): outputs
    zero past each length, final states from the true last step,
    bidirectional reverse over the valid portion only — equal to torch's
    packed-sequence behavior with copied weights."""
    paddle.seed(0)
    net = paddle.nn.LSTM(4, 3, num_layers=1, direction="bidirect")
    tnet = torch.nn.LSTM(4, 3, num_layers=1, bidirectional=True,
                         batch_first=True)
    params = dict(net.named_parameters())
    with torch.no_grad():
        for name, _ in tnet.named_parameters():
            getattr(tnet, name).copy_(_tt(_np(params[name])))
    x = R.randn(2, 6, 4).astype(np.float32)
    lens = np.array([6, 3], np.int64)
    out, (h, c) = net(_t(x), sequence_length=_t(lens))
    packed = torch.nn.utils.rnn.pack_padded_sequence(
        _tt(x), torch.from_numpy(lens), batch_first=True,
        enforce_sorted=False)
    tout_p, (th, tc) = tnet(packed)
    tout, _ = torch.nn.utils.rnn.pad_packed_sequence(tout_p,
                                                     batch_first=True)
    o = _np(out)
    np.testing.assert_allclose(o[0], tout.detach().numpy()[0],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(o[1, :3], tout.detach().numpy()[1, :3],
                               rtol=1e-4, atol=1e-5)
    assert np.allclose(o[1, 3:], 0)
    np.testing.assert_allclose(_np(h), th.detach().numpy(), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(_np(c), tc.detach().numpy(), rtol=1e-4,
                               atol=1e-5)


def test_stacked_gru_matches_torch():
    """2-layer GRU over a sequence with copied weights (LSTM's sibling)."""
    paddle.seed(0)
    net = paddle.nn.GRU(4, 3, num_layers=2)
    tnet = torch.nn.GRU(4, 3, num_layers=2, batch_first=True)
    params = dict(net.named_parameters())
    with torch.no_grad():
        for name, _ in tnet.named_parameters():
            getattr(tnet, name).copy_(_tt(_np(params[name])))
    x = R.randn(2, 5, 4).astype(np.float32)
    out, h = net(_t(x))
    tout, th = tnet(_tt(x))
    np.testing.assert_allclose(_np(out), tout.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(_np(h), th.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("shape,out", [
    ((1, 1, 2, 2), 7),    # output larger than input (VGG on small imgs)
    ((1, 2, 5, 5), 3),    # non-divisible
    ((2, 3, 7, 9), (4, 5)),
])
def test_adaptive_pools_match_torch(shape, out):
    x = R.randn(*shape).astype(np.float32)
    o = tuple(out) if isinstance(out, tuple) else (out, out)
    np.testing.assert_allclose(
        _np(F.adaptive_avg_pool2d(_t(x), out)),
        TF.adaptive_avg_pool2d(_tt(x), o).numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        _np(F.adaptive_max_pool2d(_t(x), out)),
        TF.adaptive_max_pool2d(_tt(x), o).numpy(), rtol=1e-5, atol=1e-6)
