"""Book-tier integration tests: the reference's classic end-to-end models
(python/paddle/fluid/tests/book/) trained briefly, asserting the loss
decreases.  Each exercises a different subsystem stack:

- fit_a_line        -> static Program/Executor + SGD (test_fit_a_line.py)
- recognize_digits  -> eager conv net + Adam (test_recognize_digits.py)
- word2vec          -> embedding + NCE sampled softmax (test_word2vec
                       uses hierarchical softmax/NCE variants)
- label_semantic    -> emission net + linear-chain CRF + decoding
                       (test_label_semantic_roles.py)
- rnn_encoder_decoder -> StaticRNN seq2seq + beam-search decode
                       (test_rnn_encoder_decoder.py / machine_translation)
- recommender_system -> dual-tower embedding + cos_sim rating regression
                       (test_recommender_system.py)
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.static as static


def _np(t):
    return np.asarray(t._data)


def test_book_fit_a_line():
    rng = np.random.RandomState(0)
    w_true = rng.rand(13, 1).astype(np.float32)
    xs = rng.rand(64, 13).astype(np.float32)
    ys = xs @ w_true + 0.1

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [64, 13])
        y = static.data("y", [64, 1])
        pred = static.nn.fc(x, 1)
        loss = static.nn.mean((pred - y) * (pred - y))
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    losses = [float(np.ravel(exe.run(main, feed={"x": xs, "y": ys},
                            fetch_list=[loss])[0])[0]) for _ in range(15)]
    assert losses[-1] < 0.5 * losses[0]


def test_book_recognize_digits():
    paddle.seed(1)
    rng = np.random.RandomState(1)
    net = nn.Sequential(
        nn.Conv2D(1, 8, 5, stride=2), nn.ReLU(),
        nn.Conv2D(8, 16, 3, stride=2), nn.ReLU(),
        nn.Flatten(), nn.Linear(16 * 5 * 5, 10),
    )
    opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                parameters=net.parameters())
    img = paddle.to_tensor(rng.rand(64, 1, 28, 28).astype(np.float32))
    lbl = paddle.to_tensor(rng.randint(0, 10, (64, 1)).astype(np.int64))
    losses = []
    for _ in range(30):
        loss = paddle.mean(F.softmax_with_cross_entropy(net(img), lbl))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(_np(loss)))
    assert losses[-1] < 0.6 * losses[0]


def test_book_word2vec_nce():
    """Skip-gram with NCE loss over a toy corpus with strong structure."""
    paddle.seed(2)
    rng = np.random.RandomState(2)
    V, D, B = 50, 16, 128
    emb = nn.Embedding(V, D)
    nce_w = paddle.create_parameter([V, D], "float32")
    nce_b = paddle.create_parameter([V], "float32")
    # corpus: word w is followed by (w+1) % V
    center = rng.randint(0, V, (B,)).astype(np.int64)
    target = ((center + 1) % V).astype(np.int64)
    c_t = paddle.to_tensor(center)
    t_t = paddle.to_tensor(target)
    params = list(emb.parameters()) + [nce_w, nce_b]
    opt = paddle.optimizer.Adam(learning_rate=5e-2, parameters=params)
    losses = []
    for i in range(40):
        h = emb(c_t)
        cost = paddle.nce(h, nce_w, t_t, bias=nce_b, num_total_classes=V,
                          num_neg_samples=8, seed=i + 1)
        loss = paddle.mean(cost)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(_np(loss)))
    assert losses[-1] < 0.6 * losses[0]


def test_book_label_semantic_roles_crf():
    """Emission MLP + linear-chain CRF trained, then Viterbi decode beats
    random tagging on the training batch."""
    paddle.seed(3)
    rng = np.random.RandomState(3)
    B, T, V, N, D = 8, 10, 40, 5, 16
    words = rng.randint(0, V, (B, T)).astype(np.int64)
    labels = (words[:, :] % N).astype(np.int64)  # learnable mapping
    emb = nn.Embedding(V, D)
    proj = nn.Linear(D, N)
    trans = paddle.create_parameter([N + 2, N], "float32")
    lens = paddle.to_tensor(np.full((B,), T, np.int64))
    w_t = paddle.to_tensor(words)
    l_t = paddle.to_tensor(labels)
    params = list(emb.parameters()) + list(proj.parameters()) + [trans]
    opt = paddle.optimizer.Adam(learning_rate=5e-2, parameters=params)
    for _ in range(25):
        emission = proj(emb(w_t))
        ll = paddle.linear_chain_crf(emission, trans, l_t, lens)
        loss = -paddle.mean(ll)
        loss.backward()
        opt.step()
        opt.clear_grad()
    with paddle.no_grad():
        emission = proj(emb(w_t))
    path = paddle.crf_decoding(emission, trans, lens)
    acc = (_np(path) == labels).mean()
    assert acc > 0.5  # random would be 0.2


def test_book_rnn_encoder_decoder():
    """StaticRNN encoder trained to help a decoder predict shifted
    sequences; then a greedy/beam decode sanity check in eager mode."""
    T, B, V, D = 6, 8, 20, 12
    rng = np.random.RandomState(4)
    src = rng.randint(1, V, (T, B)).astype(np.int64)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [T, B], dtype="int64")
        y = static.data("y", [T, B], dtype="int64")
        emb_w = static.create_parameter([V, D], "float32")
        h0 = static.data("h0", [B, D])
        rnn = static.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            prev = rnn.memory(init=h0)
            from paddle_tpu.static.nn_static import emit
            import jax.numpy as jnp

            e = emit("lookup_table_v2",
                     [("W", emb_w), ("Ids", xt)],
                     [("Out", [B, D], "float32")],
                     lambda w, ids: w[ids.astype(jnp.int32)])
            nxt = static.nn.fc(e + prev, D, activation="tanh")
            rnn.update_memory(prev, nxt)
            rnn.step_output(nxt)
        hs = rnn()  # (T, B, D)
        logits = static.nn.fc(
            static.nn.reshape(hs, [T * B, D]), V)
        loss = static.nn.mean(static.nn.softmax_with_cross_entropy(
            logits, static.nn.reshape(y, [T * B, 1])))
        opt = paddle.optimizer.Adam(learning_rate=1e-2)
        opt.minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    tgt = np.roll(src, -1, axis=0)
    feed = {"x": src, "y": tgt, "h0": np.zeros((B, D), np.float32)}
    losses = [float(np.ravel(exe.run(main, feed=feed,
                                     fetch_list=[loss])[0])[0])
              for _ in range(20)]
    assert losses[-1] < 0.8 * losses[0]


def test_book_recommender_system():
    """Dual-tower: user/item embeddings, cos_sim scaled to a rating,
    squared-error regression (test_recommender_system.py)."""
    paddle.seed(5)
    rng = np.random.RandomState(5)
    U, I, D, B = 30, 40, 8, 64
    u_emb = nn.Embedding(U, D)
    i_emb = nn.Embedding(I, D)
    users = rng.randint(0, U, (B,)).astype(np.int64)
    items = rng.randint(0, I, (B,)).astype(np.int64)
    ratings = ((users + items) % 5 + 1).astype(np.float32).reshape(B, 1)
    u_t, i_t = paddle.to_tensor(users), paddle.to_tensor(items)
    r_t = paddle.to_tensor(ratings)
    params = list(u_emb.parameters()) + list(i_emb.parameters())
    opt = paddle.optimizer.Adam(learning_rate=5e-2, parameters=params)
    losses = []
    for _ in range(20):
        sim = paddle.cos_sim(u_emb(u_t), i_emb(i_t))
        pred = paddle.scale(sim, 5.0)
        loss = paddle.mean(paddle.square(pred - r_t))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(_np(loss)))
    assert losses[-1] < 0.8 * losses[0]


def test_book_image_classification():
    """test_image_classification.py: a small VGG-style conv net on
    CIFAR-shaped data through the STATIC Program/Executor with
    batch_norm + dropout + Momentum — the config-2 subsystem stack."""
    paddle.seed(7)
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            img = static.data("img", [8, 3, 32, 32])
            lbl = static.data("lbl", [8, 1], dtype="int64")
            x = static.nn.conv2d(img, 16, 3, padding=1, act="relu")
            x = static.nn.batch_norm(x, act="relu")
            x = static.nn.pool2d(x, pool_size=2, pool_type="max",
                                 pool_stride=2)
            x = static.nn.conv2d(x, 32, 3, padding=1, act="relu")
            x = static.nn.pool2d(x, global_pooling=True, pool_type="avg")
            x = static.nn.flatten(x, axis=1)
            x = static.nn.dropout(x, dropout_prob=0.1)
            logits = static.nn.fc(x, 10)
            loss = static.nn.mean(
                static.nn.softmax_with_cross_entropy(logits, lbl))
            paddle.optimizer.Momentum(learning_rate=0.05,
                                      momentum=0.9).minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"img": rng.rand(8, 3, 32, 32).astype(np.float32),
                "lbl": rng.randint(0, 10, (8, 1)).astype(np.int64)}
        # fresh dropout masks per step (post-fix behavior) make the
        # trajectory noisier than the old fixed-mask bug did: average
        # the tail instead of trusting a single step
        losses = [float(np.ravel(
                      exe.run(main, feed=feed, fetch_list=[loss])[0])[0])
                  for _ in range(60)]
        assert np.mean(losses[-5:]) < 0.6 * losses[0], losses[::10]
    finally:
        paddle.disable_static()


def test_book_understand_sentiment_lstm():
    """test_understand_sentiment (book chapter): embedding -> LSTM ->
    sequence-last pooling -> classifier, eager + Adam on Imdb-shaped
    data — the recurrent-stack book leg."""
    paddle.seed(11)
    rng = np.random.RandomState(1)
    B, T, V, H = 8, 16, 200, 32
    ids = paddle.to_tensor(rng.randint(1, V, (B, T)).astype(np.int64))
    lbl = paddle.to_tensor(rng.randint(0, 2, (B, 1)).astype(np.int64))

    emb = nn.Embedding(V, H)
    lstm = nn.LSTM(H, H)
    head = nn.Linear(H, 2)
    params = (list(emb.parameters()) + list(lstm.parameters())
              + list(head.parameters()))
    opt = paddle.optimizer.Adam(learning_rate=2e-3, parameters=params)
    losses = []
    for _ in range(20):
        h, _ = lstm(emb(ids))
        logits = head(h[:, -1])
        loss = paddle.mean(F.softmax_with_cross_entropy(logits, lbl))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(_np(loss)))
    assert losses[-1] < 0.6 * losses[0], losses[::5]


def test_book_machine_translation():
    """The remaining reference book chapter (test_machine_translation.py):
    attention seq2seq trained on a tiny reverse-copy task, then beam
    search inference through BeamSearchDecoder + dynamic_decode."""
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F

    V, D, B, T = 16, 16, 8, 5
    EOS = V  # reserved </s>: never appears in data (tokens are 1..V-1)
    paddle.seed(0)
    rng = np.random.RandomState(7)

    emb = nn.Embedding(V + 1, D)  # + reserved </s> row
    enc = nn.GRU(D, D)
    dec_cell = nn.GRUCell(2 * D, D)
    out_fc = nn.Linear(D, V + 1)  # logits include </s>
    params = (list(emb.parameters()) + list(enc.parameters())
              + list(dec_cell.parameters()) + list(out_fc.parameters()))
    opt = paddle.optimizer.Adam(learning_rate=5e-3, parameters=params)

    def attention(h, enc_out):
        # dot-product attention over encoder states
        scores = paddle.matmul(enc_out, h.unsqueeze(-1)).squeeze(-1)
        w = F.softmax(scores, axis=-1)
        return paddle.matmul(w.unsqueeze(1), enc_out).squeeze(1)

    def step_loss(src, tgt):
        enc_out, _ = enc(emb(src))             # (B, T, D)
        h = enc_out[:, -1]
        loss = 0
        prev = paddle.to_tensor(np.zeros((B,), np.int64))  # <s>=0
        for t in range(T):
            ctx = attention(h, enc_out)
            inp = paddle.concat([emb(prev), ctx], axis=-1)
            h, _ = dec_cell(inp, h)
            logits = out_fc(h)
            loss = loss + paddle.mean(F.softmax_with_cross_entropy(
                logits, tgt[:, t:t + 1]))
            prev = tgt[:, t]                    # teacher forcing
        return loss / T

    src_np = rng.randint(1, V, (B, T)).astype(np.int64)
    tgt_np = src_np[:, ::-1].copy()             # translation = reversal
    src, tgt = paddle.to_tensor(src_np), paddle.to_tensor(tgt_np)
    losses = []
    for _ in range(25):
        loss = step_loss(src, tgt)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(_np(loss)))
    assert losses[-1] < 0.6 * losses[0], losses[::6]

    # inference: beam search over the trained decoder
    enc_out, _ = enc(emb(src))
    h0 = enc_out[:, -1]

    class _Wrap:
        """BeamSearchDecoder cell contract: ids + states -> logits,
        states (state pytree mirrors the inits tuple)."""

        def __call__(self, ids, states):
            h = states[0] if isinstance(states, (list, tuple)) else states
            ctx = attention(h, enc_out_rep)
            inp = paddle.concat([emb(ids), ctx], axis=-1)
            h2, _ = dec_cell(inp, h)
            return out_fc(h2), (h2,)

    K = 3
    enc_out_rep = nn.BeamSearchDecoder.tile_beam_merge_with_batch(
        enc_out, K)
    dec = nn.BeamSearchDecoder(_Wrap(), start_token=0, end_token=EOS,
                               beam_size=K)
    out, scores = nn.dynamic_decode(dec, inits=(h0,), max_step_num=T)
    arr = _np(out)
    assert arr.shape[0] == B and arr.shape[2] == K
    # EOS is reserved (not a data token); decoding may emit it, but the
    # trained model should mostly open with genuine vocab predictions
    assert arr.max() <= V
    assert (arr[:, 0, 0] < V).mean() > 0.5
    assert np.isfinite(_np(scores)).all()
