"""2-process data-parallel trainer used by the launch/spawn dist tests.

check_with_place contract (reference test_dist_base.py:1266): per-step
distributed losses must match the single-process run.  Each process owns
one CPU device; jax.distributed.initialize is the coordination-service
analogue of the reference's TCP nccl-id broadcast
(gen_comm_id_helper.cc:297).
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_fit_a_line(rank, n, mesh):
    """Shared fixture: deterministic fit-a-line data (global batch 8,
    sharded over ranks) + the jitted DP step.  Used by this trainer and
    dist_preempt_trainer so the two stay one contract."""
    import jax
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P, NamedSharding

    from paddle_tpu.parallel.collective import shard_map

    rng = np.random.RandomState(0)
    X = rng.rand(8, 3).astype(np.float32)
    Wt = rng.rand(3, 1).astype(np.float32)
    Y = X @ Wt + 0.1
    per = 8 // n
    Xl = X[rank * per:(rank + 1) * per]
    Yl = Y[rank * per:(rank + 1) * per]
    sh = NamedSharding(mesh, P("data", None))
    if n > 1:
        xs = jax.make_array_from_process_local_data(sh, Xl)
        ys = jax.make_array_from_process_local_data(sh, Yl)
    else:
        xs = jax.device_put(X, sh)
        ys = jax.device_put(Y, sh)

    def local_step(w, b, x, y):
        def loss_fn(w, b):
            pred = x @ w + b
            return jnp.mean((pred - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(w, b)
        loss = jax.lax.pmean(loss, "data")
        gw, gb = (jax.lax.pmean(g, "data") for g in grads)
        return loss, w - 0.5 * gw, b - 0.5 * gb

    step = jax.jit(shard_map(
        local_step, mesh,
        in_specs=(P(), P(), P("data", None), P("data", None)),
        out_specs=(P(), P(), P())))
    return xs, ys, step


def train_dp(out_path=None):
    # exactly one local device per process: the parent test env carries an
    # 8-device XLA_FLAGS, so override rather than setdefault
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if n > 1:
        jax.distributed.initialize(
            coordinator_address=os.environ["PADDLE_MASTER"],
            num_processes=n, process_id=rank)
    import numpy as np
    import jax.numpy as jnp

    from paddle_tpu.parallel.env import init_parallel_env, global_mesh

    init_parallel_env()
    mesh = global_mesh()
    xs, ys, step = build_fit_a_line(rank, n, mesh)
    w = jnp.zeros((3, 1), jnp.float32)
    b = jnp.zeros((1,), jnp.float32)
    losses = []
    for _ in range(3):
        loss, w, b = step(w, b, xs, ys)
        losses.append(float(np.asarray(loss)))
    if out_path and rank == 0:
        with open(out_path, "w") as f:
            json.dump(losses, f)
    print("LOSSES " + json.dumps(losses), flush=True)
    return losses


def spawn_entry(out_dir):
    """Entry for paddle.distributed.spawn (rank env set by _wrap)."""
    rank = os.environ.get("PADDLE_TRAINER_ID", "0")
    train_dp(os.path.join(out_dir, "spawn_losses.json")
             if rank == "0" else None)


if __name__ == "__main__":
    train_dp(sys.argv[1] if len(sys.argv) > 1 else None)
