"""paddle.distribution tests: moments/entropy/log_prob against closed
forms, sampling statistics, gradient flow through parameters."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distribution import Uniform, Normal, Categorical


def _np(t):
    return np.asarray(t._data)


def test_uniform():
    u = Uniform(1.0, 3.0)
    s = u.sample([2000], seed=5)
    arr = _np(s)
    assert arr.min() >= 1.0 and arr.max() < 3.0
    assert abs(arr.mean() - 2.0) < 0.1
    np.testing.assert_allclose(float(_np(u.entropy())), np.log(2.0),
                               rtol=1e-6)
    lp = u.log_prob(paddle.to_tensor(np.array([2.0, 5.0], np.float32)))
    np.testing.assert_allclose(_np(lp)[0], -np.log(2.0), rtol=1e-6)
    assert _np(lp)[1] == -np.inf


def test_normal_and_kl():
    n = Normal(0.0, 2.0)
    s = _np(n.sample([4000], seed=6))
    assert abs(s.mean()) < 0.2 and abs(s.std() - 2.0) < 0.2
    want_ent = 0.5 + 0.5 * np.log(2 * np.pi) + np.log(2.0)
    np.testing.assert_allclose(float(_np(n.entropy())), want_ent, rtol=1e-6)
    lp = float(_np(n.log_prob(paddle.to_tensor(np.float32(0.0)))))
    np.testing.assert_allclose(lp, -np.log(2.0) - 0.5 * np.log(2 * np.pi),
                               rtol=1e-6)
    kl = float(_np(n.kl_divergence(Normal(0.0, 2.0))))
    assert abs(kl) < 1e-6
    kl2 = float(_np(n.kl_divergence(Normal(1.0, 2.0))))
    np.testing.assert_allclose(kl2, 0.5 * 1.0 / 4.0, rtol=1e-5)


def test_normal_param_grad():
    loc = paddle.to_tensor(np.float32(0.5))
    loc.stop_gradient = False
    n = Normal(loc, 1.0)
    lp = n.log_prob(paddle.to_tensor(np.float32(1.5)))
    lp.backward()
    # d/dmu log N = (v - mu)/var = 1.0
    np.testing.assert_allclose(float(np.asarray(loc.grad._data)), 1.0,
                               rtol=1e-5)


def test_categorical():
    logits = np.log(np.array([0.2, 0.3, 0.5], np.float32))
    c = Categorical(logits)
    ent = float(_np(c.entropy()))
    want = -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5))
    np.testing.assert_allclose(ent, want, rtol=1e-5)
    p = _np(c.probs(paddle.to_tensor(np.array(2, np.int64))))
    np.testing.assert_allclose(float(p), 0.5, rtol=1e-5)
    s = _np(c.sample([3000], seed=7))
    frac2 = (s == 2).mean()
    assert abs(frac2 - 0.5) < 0.05
    kl = float(_np(c.kl_divergence(Categorical(logits))))
    assert abs(kl) < 1e-6


def test_regularizer_module():
    from paddle_tpu.regularizer import L1Decay, L2Decay

    assert L2Decay(1e-4)._coeff == 1e-4
    assert L1Decay(1e-3)._coeff == 1e-3
