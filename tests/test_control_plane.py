"""The self-healing control plane: FleetSupervisor (serving/control.py)
plus the fleet-scaling primitives it drives (add_replica /
remove_replica) and the watchdog's synthetic ping probes.

The router is mechanism, the supervisor is policy — so the tests
drive tick() synchronously (deterministic) and reserve the background
thread for one end-to-end resurrection:

- RESURRECT: dead replicas get restart(wait=False), RESPECTING the
  router's respawn discipline — backoff owed means retry next tick,
  a crash-loop streak past max_respawns is left for the operator.
- SCALE UP: only after `sustain_ticks` CONSECUTIVE pressure ticks,
  only with a spec_factory, only below max_replicas.  On a role-split
  fleet pressure is PER CLASS: TTFT EWMA presses the prefill class,
  decode slot occupancy presses the decode class, queue depth presses
  both — and the starved class's name reaches the spec_factory.
- SCALE DOWN: only after `idle_ticks` consecutive fully-idle ticks,
  only the supervisor's OWN spawns (LIFO), never below min_replicas —
  the operator's configured fleet is never shrunk.
"""
import time

import pytest

from paddle_tpu import generation as gen
from paddle_tpu.profiler.monitor import StatRegistry
from paddle_tpu.serving import fleet as fleet_mod
from paddle_tpu.serving.admission import ServingError
from paddle_tpu.serving.control import FleetSupervisor, SupervisorConfig
from paddle_tpu.serving.fleet import (FleetConfig, FleetRouter,
                                      ReplicaSpec)

from dist_capability import (SUBPROC_SKIP_REASON,  # noqa: E402
                             subprocess_replicas_available)
from gen_oracle import greedy_oracle as _ref  # noqa: E402

needs_subproc = pytest.mark.skipif(
    not subprocess_replicas_available(), reason=SUBPROC_SKIP_REASON)

SYSTEM = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]


@pytest.fixture(autouse=True)
def _fresh_fleet_stats():
    reg = StatRegistry.instance()
    for name in list(reg.stats()):
        if name.startswith(fleet_mod.PREFIX):
            reg.get_stat(name).reset()
    yield


@pytest.fixture(scope="module")
def model():
    return gen.TinyCausalLM(vocab_size=48, num_layers=2, num_heads=2,
                            head_dim=8, seed=3)


def _cfg(**kw):
    base = dict(max_decode_slots=4, num_pages=64, page_size=4,
                prefix_cache=True)
    base.update(kw)
    return gen.GenerationConfig(**base)


def _stat(name):
    return StatRegistry.instance().get_stat(name).get()


def _fleet(model, n=1, **kw):
    specs = [ReplicaSpec(f"r{i}", model, _cfg()) for i in range(n)]
    base = dict(start=False, seed=0)
    base.update(kw)
    return FleetRouter(specs, FleetConfig(**base))


# ----------------------------- config ------------------------------------


def test_supervisor_config_validates():
    cfg = SupervisorConfig()
    assert cfg.sustain_ticks == 3 and cfg.idle_ticks == 8
    with pytest.raises(ValueError, match="interval_s"):
        SupervisorConfig(interval_s=0)
    with pytest.raises(ValueError, match="scale_up_queue_depth"):
        SupervisorConfig(scale_up_queue_depth=-1)
    with pytest.raises(ValueError, match="scale_up_ttft_s"):
        SupervisorConfig(scale_up_ttft_s=0)
    with pytest.raises(ValueError, match="sustain_ticks"):
        SupervisorConfig(sustain_ticks=0)
    with pytest.raises(ValueError, match="idle_ticks"):
        SupervisorConfig(idle_ticks=0)


# -------------------------- fleet scaling API -----------------------------


def test_add_remove_replica_router_primitives(model):
    fl = _fleet(model, n=1)
    try:
        with pytest.raises(ValueError, match="duplicate"):
            fl.add_replica(ReplicaSpec("r0", model, _cfg()))
        with pytest.raises(KeyError):
            fl.remove_replica("ghost")
        name = fl.add_replica(ReplicaSpec("late", model, _cfg()))
        assert name == "late"
        # the new replica is immediately routable: saturate r0's
        # admission so the ladder spills onto it
        per_before = fl.stats_snapshot()["replicas"]
        assert "late" in per_before
        h = fl.submit(SYSTEM, max_new_tokens=4)
        fl.run_until_idle()
        assert h.result(timeout=10).token_ids == _ref(model, SYSTEM, 4)
        fl.remove_replica("late")
        assert "late" not in fl.stats_snapshot()["replicas"]
    finally:
        fl.shutdown()


def test_replica_count_gauge_tracks_scaling(model):
    fl = _fleet(model, n=1)
    try:
        fl.stats_snapshot()
        assert _stat(fleet_mod.REPLICA_COUNT) == 1
        fl.add_replica(ReplicaSpec("x", model, _cfg()))
        assert _stat(fleet_mod.REPLICA_COUNT) == 2
        fl.remove_replica("x")
        assert _stat(fleet_mod.REPLICA_COUNT) == 1
    finally:
        fl.shutdown()


# ----------------------------- resurrection -------------------------------


def test_tick_resurrects_dead_replica(model):
    """Deterministic resurrection: mark the replica dead (a clean
    streak owes no backoff), one tick heals it, and it serves."""
    fl = _fleet(model, n=1)
    sup = FleetSupervisor(fl)
    try:
        rep = fl._replicas["r0"]
        rep.transport.stop()
        rep.state = "dead"
        rep.died_at = time.monotonic()
        rep.respawns = 0               # died after a long healthy run
        out = sup.tick()
        assert out["healed"] == 1
        assert rep.state == "serving"
        assert _stat(fleet_mod.SUPERVISOR_RESTART_TOTAL) == 1
        h = fl.submit(SYSTEM, max_new_tokens=4)
        fl.run_until_idle()
        assert h.result(timeout=10).token_ids == _ref(model, SYSTEM, 4)
    finally:
        sup.stop()
        fl.shutdown()


def test_tick_respects_respawn_backoff(model):
    """A quick death owes backoff: tick() must NOT bypass it (the
    wait=False restart raises typed and the supervisor retries next
    tick) — then heals once the debt is paid."""
    fl = _fleet(model, n=1, respawn_backoff_s=5.0)
    sup = FleetSupervisor(fl)
    try:
        rep = fl._replicas["r0"]
        rep.transport.stop()
        rep.state = "dead"
        rep.respawns = 1               # quick death: streak of one
        rep.died_at = time.monotonic()
        assert sup.tick()["healed"] == 0        # 5s still owed
        assert rep.state == "dead"
        rep.died_at = time.monotonic() - 10.0   # debt paid
        assert sup.tick()["healed"] == 1
        assert rep.state == "serving"
    finally:
        sup.stop()
        fl.shutdown()


def test_tick_respects_crash_loop_cap(model):
    """A streak past max_respawns is the operator's problem: the
    supervisor leaves it dead, and reset_respawn() is the documented
    override that lets the next tick heal."""
    fl = _fleet(model, n=1, max_respawns=2, respawn_backoff_s=0.0)
    sup = FleetSupervisor(fl)
    try:
        rep = fl._replicas["r0"]
        rep.transport.stop()
        rep.state = "dead"
        rep.respawns = 3               # > max_respawns: crash loop
        rep.died_at = time.monotonic()
        for _ in range(3):
            assert sup.tick()["healed"] == 0
        assert rep.state == "dead"
        fl.reset_respawn("r0")
        assert sup.tick()["healed"] == 1
        assert rep.state == "serving"
    finally:
        sup.stop()
        fl.shutdown()


@pytest.mark.slow
@needs_subproc
def test_supervisor_resurrects_sigkilled_worker_end_to_end(model):
    """THE acceptance path: a SIGKILLed subprocess replica comes back
    with ZERO router calls from this test body — the watchdog detects
    the death, the supervisor's background loop restarts it, and a
    fresh submit serves from the resurrected worker."""
    fl = _fleet(model, n=1, start=True, transport="proc",
                heartbeat_dead_after=2.0, watchdog_interval_s=0.1,
                respawn_backoff_s=0.05)
    sup = FleetSupervisor(fl, config=SupervisorConfig(interval_s=0.1))
    try:
        sup.start()
        h = fl.submit(SYSTEM, max_new_tokens=4)
        assert h.result(timeout=60).token_ids == _ref(model, SYSTEM, 4)
        fl._replicas["r0"].transport.kill()
        deadline = time.monotonic() + 60
        while fl._replicas["r0"].state != "serving":
            assert time.monotonic() < deadline, "never resurrected"
            time.sleep(0.1)
        # the stat lands just after restart() flips the state — allow
        # the supervisor thread that instant
        while _stat(fleet_mod.SUPERVISOR_RESTART_TOTAL) < 1:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        h2 = fl.submit(SYSTEM + [7], max_new_tokens=4)
        assert h2.result(timeout=60).token_ids == \
            _ref(model, SYSTEM + [7], 4)
    finally:
        sup.stop()
        fl.shutdown()


# ------------------------------ autoscaler --------------------------------


def _pressured_fleet_and_sup(model, sustain=2, **fleet_kw):
    kw = dict(max_replicas=3, min_replicas=1)
    kw.update(fleet_kw)
    fl = _fleet(model, n=1, **kw)
    sup = FleetSupervisor(
        fl, spec_factory=lambda i: ReplicaSpec(f"auto{i}", model,
                                               _cfg()),
        config=SupervisorConfig(scale_up_queue_depth=0.5,
                                sustain_ticks=sustain, idle_ticks=2))
    return fl, sup


def test_autoscaler_spawns_only_after_sustained_pressure(model):
    fl, sup = _pressured_fleet_and_sup(model, sustain=2)
    try:
        # start=False + no stepping: submits sit in the queue, so
        # every tick reads depth >= 0.5 — deterministic pressure
        hs = [fl.submit(SYSTEM, max_new_tokens=4) for _ in range(4)]
        first = sup.tick()
        assert not first["spawned"]        # one pressure tick != sustained
        second = sup.tick()
        assert second["spawned"]           # sustained: spawn exactly one
        assert "auto0" in fl._replicas
        assert _stat(fleet_mod.AUTOSCALE_SPAWNED) == 1
        fl.run_until_idle()
        for h in hs:
            assert h.result(timeout=10).finish_reason == "length"
    finally:
        sup.stop()
        fl.shutdown()


def test_autoscaler_respects_max_replicas(model):
    fl, sup = _pressured_fleet_and_sup(model, sustain=1,
                                       max_replicas=2)
    try:
        hs = [fl.submit(SYSTEM, max_new_tokens=4) for _ in range(4)]
        assert sup.tick()["spawned"]       # 1 -> 2
        for _ in range(4):                 # at the cap: never a third
            assert not sup.tick()["spawned"]
        assert len(fl._replicas) == 2
        fl.run_until_idle()
        for h in hs:
            h.result(timeout=10)
    finally:
        sup.stop()
        fl.shutdown()


def test_autoscaler_drains_only_own_spawns_to_min(model):
    """After the load passes, sustained idle drains the supervisor's
    spawn — and ONLY its spawn: the operator's base replica survives
    unbounded idle ticks."""
    fl, sup = _pressured_fleet_and_sup(model, sustain=1)
    try:
        hs = [fl.submit(SYSTEM, max_new_tokens=4) for _ in range(4)]
        assert sup.tick()["spawned"]
        fl.run_until_idle()
        for h in hs:
            h.result(timeout=10)
        drains = [sup.tick()["drained"] for _ in range(3)]
        assert drains == [False, True, False]   # idle_ticks=2, LIFO
        assert "auto0" not in fl._replicas
        assert "r0" in fl._replicas
        assert _stat(fleet_mod.AUTOSCALE_DRAINED) == 1
        for _ in range(6):                  # base fleet never shrinks
            assert not sup.tick()["drained"]
        assert "r0" in fl._replicas
    finally:
        sup.stop()
        fl.shutdown()


def test_autoscaler_inert_without_spec_factory(model):
    fl = _fleet(model, n=1, max_replicas=3)
    sup = FleetSupervisor(fl, config=SupervisorConfig(
        scale_up_queue_depth=0.5, sustain_ticks=1))
    try:
        fl.submit(SYSTEM, max_new_tokens=4)
        for _ in range(3):
            assert not sup.tick()["spawned"]
        assert len(fl._replicas) == 1
        fl.run_until_idle()
    finally:
        sup.stop()
        fl.shutdown()


def test_supervisor_config_validates_slot_occupancy():
    assert SupervisorConfig().scale_up_slot_occupancy is None
    assert SupervisorConfig(
        scale_up_slot_occupancy=1.0).scale_up_slot_occupancy == 1.0
    for bad in (0, -0.5, 1.5):
        with pytest.raises(ValueError, match="scale_up_slot_occupancy"):
            SupervisorConfig(scale_up_slot_occupancy=bad)


def _split_fleet_and_sup(model, roles=("prefill", "decode"), **sup_kw):
    """A role-split fleet whose pressure signals the tests FABRICATE
    (cached load_info + describe state — exactly what _survey reads),
    plus a role-recording spec_factory."""
    specs = [ReplicaSpec(f"r{i}", model, _cfg(), role=role)
             for i, role in enumerate(roles)]
    fl = FleetRouter(specs, FleetConfig(start=False, seed=0,
                                        max_replicas=4))
    spawned_roles = []

    def factory(i, role="mixed"):
        spawned_roles.append(role)
        return ReplicaSpec(f"auto{i}", model, _cfg(), role=role)

    kw = dict(scale_up_queue_depth=100.0, sustain_ticks=1)
    kw.update(sup_kw)
    sup = FleetSupervisor(fl, spec_factory=factory,
                          config=SupervisorConfig(**kw))
    return fl, sup, spawned_roles


def test_autoscaler_decode_pressure_spawns_decode_replica(model):
    """Saturated decode slots press ONLY the decode class: the spawn
    carries role="decode", and the prefill class stays quiet."""
    fl, sup, roles = _split_fleet_and_sup(
        model, scale_up_slot_occupancy=0.9)
    try:
        rep = fl._replicas["r1"]          # the decode replica
        rep._describe = {"max_decode_slots": 4}
        rep.transport.load_info = lambda: {
            "queue_depth": 0, "active": 4, "idle": False}
        report = sup.tick()
        assert report["pressure"] == {"prefill": False, "decode": True}
        assert report["spawned"]
        assert roles == ["decode"]
        assert fl._replicas["auto0"].role == "decode"
    finally:
        sup.stop()
        fl.shutdown()


def test_autoscaler_ttft_pressure_spawns_prefill_replica(model):
    """A climbing TTFT EWMA presses ONLY the prefill class — decode
    capacity would not buy admission latency."""
    fl, sup, roles = _split_fleet_and_sup(model, scale_up_ttft_s=0.5)
    try:
        fl._replicas["r0"].ttft_ewma = 2.0    # the prefill replica
        report = sup.tick()
        assert report["pressure"] == {"prefill": True, "decode": False}
        assert report["spawned"]
        assert roles == ["prefill"]
    finally:
        sup.stop()
        fl.shutdown()


def test_autoscaler_skewed_load_scales_classes_independently(model):
    """Acceptance: a skewed prefill-heavy THEN decode-heavy load
    scales each class independently — each class keeps its own sustain
    streak, and relieving one class does not bleed into the other."""
    fl, sup, roles = _split_fleet_and_sup(
        model, scale_up_ttft_s=0.5, scale_up_slot_occupancy=0.9,
        sustain_ticks=2)
    try:
        pre, dec = fl._replicas["r0"], fl._replicas["r1"]
        # phase 1: prefill-heavy (TTFT climbs), decode healthy
        pre.ttft_ewma = 2.0
        assert not sup.tick()["spawned"]      # streak 1 of 2
        r = sup.tick()                        # sustained: spawn
        assert r["spawned"] and roles == ["prefill"]
        # phase 2: prefill relieved, decode slots saturate — the
        # decode class starts its OWN streak from zero
        pre.ttft_ewma = 0.0
        dec._describe = {"max_decode_slots": 4}
        dec.transport.load_info = lambda: {
            "queue_depth": 0, "active": 4, "idle": False}
        first = sup.tick()
        assert first["pressure"] == {"prefill": False, "decode": True}
        assert not first["spawned"]           # decode streak 1 of 2
        assert sup.tick()["spawned"]
        assert roles == ["prefill", "decode"]
        assert _stat(fleet_mod.AUTOSCALE_SPAWNED) == 2
    finally:
        sup.stop()
        fl.shutdown()


def test_autoscaler_homogeneous_fleet_keeps_single_mixed_class(model):
    """No role split -> one "mixed" pressure class (the pre-split
    single-counter behavior) and plain factory(i) spec factories keep
    working unchanged."""
    fl, sup = _pressured_fleet_and_sup(model, sustain=1)
    try:
        fl.submit(SYSTEM, max_new_tokens=4)
        report = sup.tick()
        assert set(report["pressure"]) == {"mixed"}
        assert report["spawned"]
        fl.run_until_idle()
    finally:
        sup.stop()
        fl.shutdown()


def test_supervisor_context_manager_runs_background_loop(model):
    fl = _fleet(model, n=1)
    rep = fl._replicas["r0"]
    rep.transport.stop()
    rep.state = "dead"
    rep.died_at = time.monotonic()
    rep.respawns = 0
    try:
        with FleetSupervisor(
                fl, config=SupervisorConfig(interval_s=0.05)) as sup:
            sup.start()
            deadline = time.monotonic() + 10
            while rep.state != "serving":
                assert time.monotonic() < deadline
                time.sleep(0.05)
        assert sup._thread is None         # stop() joined it
    finally:
        fl.shutdown()


# ----------------------------- ping probes --------------------------------


def test_watchdog_ping_probe_recovers_idle_breaker(model):
    """An OPEN breaker on an IDLE fleet: no client traffic will ever
    probe the half-open slot, so the watchdog's synthetic ping must —
    one sweep after the cooldown, the breaker is closed again."""
    fl = _fleet(model, n=1, start=True, breaker_cooldown_s=0.05,
                watchdog_interval_s=0.05)
    try:
        rep = fl._replicas["r0"]
        for _ in range(fl.config.breaker_threshold):
            rep.breaker.record_failure()
        assert rep.breaker.state == "open"
        time.sleep(0.1)                    # cooldown elapses
        deadline = time.monotonic() + 10
        while rep.breaker.state != "closed":
            assert time.monotonic() < deadline
            fl.stats_snapshot()            # drives the watchdog sweep
            time.sleep(0.05)
        assert _stat(fleet_mod.PING_PROBE_TOTAL) >= 1
    finally:
        fl.shutdown()


def test_ping_probe_failure_reopens_breaker(model):
    """A half-open probe against a replica whose engine is GONE must
    re-open the breaker (typed failure), not close it."""
    fl = _fleet(model, n=1, start=True, breaker_cooldown_s=0.05,
                watchdog_interval_s=0.05)
    try:
        rep = fl._replicas["r0"]
        rep.transport.engine.shutdown()    # ping now raises typed
        for _ in range(fl.config.breaker_threshold):
            rep.breaker.record_failure()
        time.sleep(0.1)
        fl.stats_snapshot()
        assert rep.breaker.state == "open"
    finally:
        fl.shutdown()
