"""Top-level namespace parity: paddle.tensor / linalg / callbacks / hub /
dataset / reader / sysconfig — the thin re-export and legacy modules the
reference exposes (python/paddle/{tensor,linalg.py,callbacks.py,hub.py,
dataset/,reader/,sysconfig.py}).
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


def test_tensor_namespace_modules():
    import paddle_tpu.tensor as T

    x = paddle.to_tensor(np.array([[4.0, 1.0]], np.float32))
    np.testing.assert_allclose(
        np.asarray(T.math.add(x, x)._data), [[8.0, 2.0]])
    vals, idx = T.search.topk(x, k=1)
    assert float(np.asarray(vals._data)) == 4.0
    assert T.linalg.matmul is paddle.matmul
    assert float(np.asarray(T.stat.mean(x)._data)) == 2.5
    assert bool(np.asarray(T.logic.equal_all(x, x)._data))
    assert T.random.randn([2, 2]).shape == [2, 2]
    out = T.creation.full([2], 3.0)
    np.testing.assert_allclose(np.asarray(out._data), [3.0, 3.0])


def test_linalg_namespace():
    import paddle_tpu.linalg as L

    a = np.array([[4.0, 0.0], [0.0, 9.0]], np.float32)
    c = np.asarray(L.cholesky(paddle.to_tensor(a))._data)
    np.testing.assert_allclose(c, [[2.0, 0.0], [0.0, 3.0]], atol=1e-6)
    inv = np.asarray(L.inv(paddle.to_tensor(a))._data)
    np.testing.assert_allclose(inv @ a, np.eye(2), atol=1e-5)


def test_callbacks_namespace_and_reduce_lr_on_plateau():
    import paddle_tpu.callbacks as C

    assert C.ModelCheckpoint and C.EarlyStopping and C.VisualDL

    # ReduceLROnPlateau drops the LR after `patience` stagnant epochs
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi import Model

    net = nn.Linear(4, 2)
    model = Model(net)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    model._optimizer = opt
    cb = C.ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2,
                             verbose=0)
    cb.model = model
    cb.on_epoch_end(0, {"loss": 1.0})
    cb.on_epoch_end(1, {"loss": 1.0})
    assert abs(opt.get_lr() - 0.1) < 1e-9  # patience not yet exhausted
    cb.on_epoch_end(2, {"loss": 1.0})
    assert abs(opt.get_lr() - 0.05) < 1e-9


def test_hub_local_repo(tmp_path):
    import paddle_tpu.hub as hub

    (tmp_path / "hubconf.py").write_text(
        "dependencies = ['numpy']\n"
        "def tiny(n=2):\n"
        "    'A tiny model entrypoint.'\n"
        "    import paddle_tpu.nn as nn\n"
        "    return nn.Linear(n, n)\n")
    names = hub.list(str(tmp_path))
    assert "tiny" in names
    assert "tiny model" in hub.help(str(tmp_path), "tiny")
    layer = hub.load(str(tmp_path), "tiny", n=3)
    assert layer.weight.shape == [3, 3]
    with pytest.raises(NotImplementedError):
        hub.load("owner/repo", "tiny", source="github")


def test_dataset_reader_creators_and_decorators():
    import paddle_tpu.dataset as D
    import paddle_tpu.reader as R

    img, lbl = next(D.mnist.train()())
    assert img.shape == (784,) and -1.001 <= img.min() and 0 <= lbl < 10
    feat, _ = next(D.uci_housing.test()())
    assert np.asarray(feat).shape == (13,)

    five = list(R.firstn(D.mnist.train(), 5)())
    assert len(five) == 5
    pairs = next(R.compose(D.mnist.train(), D.mnist.train())())
    assert len(pairs) == 4  # (img, lbl) + (img, lbl)
    mapped = next(R.map_readers(lambda s: s[1], D.mnist.train())())
    assert mapped in range(10)
    buffered = list(R.firstn(R.buffered(D.mnist.test(), 4), 3)())
    assert len(buffered) == 3
    shuffled = list(R.firstn(R.shuffle(D.mnist.train(), 16), 8)())
    assert len(shuffled) == 8
    cached = R.cache(R.firstn(D.mnist.train(), 4))
    assert len(list(cached())) == len(list(cached())) == 4
    ordered = list(R.firstn(
        R.xmap_readers(lambda s: s[1], D.mnist.train(), 2, 8, order=True),
        6)())
    direct = [s[1] for s in R.firstn(D.mnist.train(), 6)()]
    assert ordered == direct


def test_sysconfig_paths():
    import paddle_tpu.sysconfig as sc

    assert os.path.isdir(sc.get_include())
    assert os.path.exists(os.path.join(sc.get_lib(), "libptn.so"))


def test_reduce_lr_on_plateau_cooldown_and_eval_prefix():
    """Cooldown epochs freeze both reductions and the patience counter;
    an eval_-prefixed metric is found via the same fallback EarlyStopping
    uses."""
    import paddle_tpu.callbacks as C
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi import Model

    net = nn.Linear(2, 2)
    model = Model(net)
    opt = paddle.optimizer.SGD(learning_rate=1.0,
                               parameters=net.parameters())
    model._optimizer = opt
    cb = C.ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1,
                             cooldown=2, verbose=0)
    cb.model = model
    cb.on_epoch_end(0, {"eval_loss": 1.0})  # baseline via eval_ prefix
    cb.on_epoch_end(1, {"eval_loss": 1.0})  # stagnant -> reduce, cooldown=2
    assert abs(opt.get_lr() - 0.5) < 1e-9
    cb.on_epoch_end(2, {"eval_loss": 1.0})  # cooldown epoch: frozen
    cb.on_epoch_end(3, {"eval_loss": 1.0})  # cooldown epoch: frozen
    assert abs(opt.get_lr() - 0.5) < 1e-9
    cb.on_epoch_end(4, {"eval_loss": 1.0})  # patience restarts cleanly
    assert abs(opt.get_lr() - 0.25) < 1e-9


def test_vision_transforms_color_and_geometry():
    """New transforms: functional color/geometry ops and their classes
    (vision/transforms functional.py + transforms.py parity)."""
    import paddle_tpu.vision.transforms as T

    rng = np.random.RandomState(0)
    img = (rng.rand(8, 8, 3) * 255).astype(np.uint8)

    np.testing.assert_array_equal(T.hflip(img), img[:, ::-1])
    np.testing.assert_array_equal(T.vflip(img), img[::-1])
    assert T.pad(img, 2).shape == (12, 12, 3)
    assert T.crop(img, 1, 1, 4, 5).shape == (4, 5, 3)
    assert T.center_crop(img, 4).shape == (4, 4, 3)
    # 90-degree rotate about the center maps (y, x) -> (x, H-1-y)
    sq = np.zeros((5, 5), np.float32)
    sq[0, 1] = 1.0
    rot = T.rotate(sq, 90)
    assert rot[3, 0] == 1.0
    # identity-ish color ops
    np.testing.assert_array_equal(T.adjust_brightness(img, 1.0), img)
    assert np.abs(T.adjust_hue(img, 0.0).astype(int)
                  - img.astype(int)).max() <= 1
    g = T.to_grayscale(img, 3)
    assert g.shape == img.shape and np.ptp(g, axis=-1).max() == 0
    # classes compose
    out = T.Compose([T.ColorJitter(0.1, 0.1, 0.1, 0.05),
                     T.RandomRotation(10), T.Grayscale(),
                     T.Pad(1), T.RandomResizedCrop(6),
                     T.ToTensor()])(img)
    assert out.shape == (1, 6, 6)


def test_summary_and_flops():
    """paddle.summary prints a per-layer table with correct totals;
    paddle.flops counts conv/linear FLOPs layer by layer
    (hapi/model_summary.py + dynamic_flops.py)."""
    from paddle_tpu.vision.models import LeNet

    net = LeNet()
    stats = paddle.summary(net, (1, 1, 28, 28))
    want = sum(int(np.prod(p.shape)) for p in net.parameters())
    assert stats["total_params"] == want == 61610

    f = paddle.flops(net, (1, 1, 28, 28))
    # exact: conv 2*4704*9 + 2*1600*150, fc 2*(400*120 + 120*84 + 84*10)
    assert f == 84672 + 480000 + 96000 + 20160 + 1680

    # custom op counters extend the table
    from paddle_tpu.nn.layers.pooling import MaxPool2D

    f2 = paddle.flops(net, (1, 1, 28, 28),
                      custom_ops={MaxPool2D: lambda l, i, o:
                                  int(np.prod(o.shape))})
    assert f2 > f


def test_incubate_hapi_quant_namespace_closure():
    import paddle_tpu.incubate as inc
    import paddle_tpu.hapi as hapi
    import paddle_tpu.quant as quant

    assert inc.auto_checkpoint and inc.softmax_mask_fuse_upper_triangle
    assert hapi.summary and hapi.flops and hapi.static_flops
    q = quant.QuantStub()
    x = paddle.to_tensor(np.ones(2, np.float32))
    assert q(x) is x
    add_layer = quant.add()
    np.testing.assert_allclose(np.asarray(add_layer(x, x)._data), 2.0)
    assert paddle.nn.container and paddle.nn.rnn and paddle.nn.transformer
