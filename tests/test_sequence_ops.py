"""Golden tests for sequence-family + classic-NLP ops (ops/sequence_ops.py).

Oracles: brute-force numpy dynamic programs (CRF enumeration over all tag
paths, Viterbi by enumeration, circular conv by definition) on tiny shapes.
"""
import itertools

import numpy as np

import paddle_tpu as paddle


def _np(t):
    return np.asarray(t._data)


def _crf_brute(emission, transition, label, length):
    """Enumerate all paths: log p(gold) - log Z."""
    start, stop, trans = transition[0], transition[1], transition[2:]
    B, T, N = emission.shape
    out = np.zeros((B, 1), np.float64)
    for b in range(B):
        L = int(length[b])

        def score(path):
            s = start[path[0]] + emission[b, 0, path[0]]
            for t in range(1, L):
                s += trans[path[t - 1], path[t]] + emission[b, t, path[t]]
            return s + stop[path[L - 1]]

        z = np.logaddexp.reduce(
            [score(p) for p in itertools.product(range(N), repeat=L)])
        out[b, 0] = score([int(v) for v in label[b, :L]]) - z
    return out


def test_linear_chain_crf_matches_enumeration():
    rng = np.random.RandomState(0)
    B, T, N = 2, 3, 3
    em = rng.randn(B, T, N).astype(np.float32)
    tr = rng.randn(N + 2, N).astype(np.float32)
    lbl = rng.randint(0, N, (B, T)).astype(np.int64)
    lens = np.array([3, 2], np.int64)
    ll = paddle.linear_chain_crf(
        paddle.to_tensor(em), paddle.to_tensor(tr),
        paddle.to_tensor(lbl), paddle.to_tensor(lens))
    want = _crf_brute(em.astype(np.float64), tr.astype(np.float64),
                      lbl, lens)
    np.testing.assert_allclose(_np(ll), want, rtol=1e-4, atol=1e-5)


def test_crf_training_improves_likelihood():
    rng = np.random.RandomState(1)
    B, T, N = 4, 5, 3
    em_t = paddle.to_tensor(rng.randn(B, T, N).astype(np.float32) * 0.1)
    tr = paddle.to_tensor(rng.randn(N + 2, N).astype(np.float32) * 0.1)
    tr.stop_gradient = False
    lbl = paddle.to_tensor(rng.randint(0, N, (B, T)).astype(np.int64))
    lens = paddle.to_tensor(np.full((B,), T, np.int64))
    opt_losses = []
    for _ in range(20):
        ll = paddle.linear_chain_crf(em_t, tr, lbl, lens)
        loss = -paddle.mean(ll)
        loss.backward()
        tr._data = tr._data - 0.5 * tr.grad._data
        tr.clear_grad()
        opt_losses.append(float(_np(loss)))
    assert opt_losses[-1] < opt_losses[0]


def test_crf_decoding_matches_enumeration():
    rng = np.random.RandomState(2)
    B, T, N = 2, 4, 3
    em = rng.randn(B, T, N).astype(np.float32)
    tr = rng.randn(N + 2, N).astype(np.float32)
    lens = np.array([4, 3], np.int64)
    path = paddle.crf_decoding(paddle.to_tensor(em), paddle.to_tensor(tr),
                               paddle.to_tensor(lens))
    got = _np(path)
    start, stop, trans = tr[0], tr[1], tr[2:]
    for b in range(B):
        L = int(lens[b])
        best, best_s = None, -np.inf
        for p in itertools.product(range(N), repeat=L):
            s = start[p[0]] + em[b, 0, p[0]]
            for t in range(1, L):
                s += trans[p[t - 1], p[t]] + em[b, t, p[t]]
            s += stop[p[L - 1]]
            if s > best_s:
                best, best_s = p, s
        np.testing.assert_array_equal(got[b, :L], best)
        assert (got[b, L:] == 0).all()


def test_nce_and_sample_logits_and_sampling_id():
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
    w = paddle.to_tensor(rng.randn(20, 8).astype(np.float32))
    b = paddle.to_tensor(rng.randn(20).astype(np.float32))
    lbl = paddle.to_tensor(rng.randint(0, 20, (4,)).astype(np.int64))
    x.stop_gradient = False
    cost = paddle.nce(x, w, lbl, bias=b, num_total_classes=20,
                      num_neg_samples=5)
    assert cost.shape == [4, 1]
    paddle.sum(cost).backward()
    assert x.grad is not None

    logits = paddle.to_tensor(rng.randn(4, 20).astype(np.float32))
    picked, ids = paddle.sample_logits(logits, lbl, num_samples=6)
    assert list(picked.shape) == [4, 7] and list(ids.shape) == [4, 7]
    np.testing.assert_array_equal(_np(ids)[:, 0], _np(lbl).reshape(-1))
    lg, iid = _np(logits), _np(ids)
    np.testing.assert_allclose(
        _np(picked), np.take_along_axis(lg, iid.astype(np.int64), axis=1))

    probs = paddle.to_tensor(np.array([[0.0, 1.0, 0.0]], np.float32))
    sid = paddle.sampling_id(probs)
    assert int(_np(sid)[0]) == 1


def test_beam_search_step_and_decode():
    # batch=1, beam=2, K=2 candidates per beam
    pre_ids = paddle.to_tensor(np.array([[5], [6]], np.int64))
    pre_scores = paddle.to_tensor(np.array([[0.0], [-1.0]], np.float32))
    ids = paddle.to_tensor(np.array([[1, 2], [3, 4]], np.int64))
    scores = paddle.to_tensor(
        np.array([[0.5, 0.1], [2.0, -3.0]], np.float32))
    sel_ids, sel_scores, parent = paddle.beam_search(
        pre_ids, pre_scores, ids, scores, beam_size=2, end_id=0)
    # best two accumulated: 2.0 (beam1,id3), 0.5 (beam0,id1)
    np.testing.assert_array_equal(_np(sel_ids).reshape(-1), [3, 1])
    np.testing.assert_allclose(_np(sel_scores).reshape(-1), [2.0, 0.5])
    np.testing.assert_array_equal(_np(parent), [1, 0])

    # finished beam (pre_id == end_id) propagates itself with frozen score
    pre_ids2 = paddle.to_tensor(np.array([[0], [6]], np.int64))
    s2, sc2, p2 = paddle.beam_search(
        pre_ids2, pre_scores, ids, scores, beam_size=2, end_id=0)
    got = list(_np(s2).reshape(-1))
    assert 0 in got  # the finished beam survived as end_id

    # decode: T=2 steps, batch=1, beam=2
    step_ids = [paddle.to_tensor(np.array([[7], [8]], np.int64)), sel_ids]
    step_parents = [paddle.to_tensor(np.array([[0], [1]], np.int64)),
                    parent]
    seqs = paddle.beam_search_decode(step_ids, step_parents, beam_size=2,
                                     end_id=0)
    out = _np(seqs)  # (T, batch, beam)
    assert out.shape == (2, 1, 2)
    # winner beam0 at final step came from parent 1 -> token 8 then 3
    np.testing.assert_array_equal(out[:, 0, 0], [8, 3])


def test_add_position_encoding():
    x = np.zeros((1, 3, 4), np.float32)
    out = _np(paddle.add_position_encoding(paddle.to_tensor(x),
                                           alpha=1.0, beta=1.0))
    # position 0: sin(0)=0, cos(0)=1
    np.testing.assert_allclose(out[0, 0], [0.0, 0.0, 1.0, 1.0], atol=1e-6)
    assert abs(out[0, 1, 0] - np.sin(1.0)) < 1e-5
    assert abs(out[0, 1, 2] - np.cos(1.0)) < 1e-5


def test_im2sequence_row_conv_conv_shift():
    x = paddle.to_tensor(
        np.arange(16).reshape(1, 1, 4, 4).astype(np.float32))
    seq = paddle.im2sequence(x, filter_size=2, stride=2)
    assert list(seq.shape) == [4, 4]
    np.testing.assert_allclose(_np(seq)[0], [0, 1, 4, 5])

    xr = paddle.to_tensor(np.ones((1, 3, 2), np.float32))
    wr = paddle.to_tensor(np.ones((2, 2), np.float32))
    out = _np(paddle.row_conv(xr, wr))
    # t=0: x[0]+x[1] = 2; t=2: only x[2] -> 1
    np.testing.assert_allclose(out[0, :, 0], [2.0, 2.0, 1.0])

    xs = np.array([[1.0, 2.0, 3.0, 4.0]], np.float32)
    ys = np.array([[0.0, 1.0, 0.0]], np.float32)  # identity kernel
    got = _np(paddle.conv_shift(paddle.to_tensor(xs), paddle.to_tensor(ys)))
    np.testing.assert_allclose(got, xs, rtol=1e-6)


def test_segment_pool():
    x = paddle.to_tensor(np.array([[1.0], [2.0], [4.0], [8.0]], np.float32))
    ids = paddle.to_tensor(np.array([0, 0, 1, 1], np.int32))
    np.testing.assert_allclose(_np(paddle.segment_sum(x, ids)),
                               [[3.0], [12.0]])
    np.testing.assert_allclose(_np(paddle.segment_mean(x, ids)),
                               [[1.5], [6.0]])
    np.testing.assert_allclose(_np(paddle.segment_max(x, ids)),
                               [[2.0], [8.0]])
    np.testing.assert_allclose(_np(paddle.segment_min(x, ids)),
                               [[1.0], [4.0]])


def test_sequence_pool_softmax_reverse():
    x = np.array([[1.0, 2.0, 9.0], [3.0, 9.0, 9.0]], np.float32)
    lens = np.array([2, 1], np.int64)
    xt, lt = paddle.to_tensor(x[..., None]), paddle.to_tensor(lens)
    np.testing.assert_allclose(
        _np(paddle.sequence_pool(xt, lt, "sum")).reshape(-1), [3.0, 3.0])
    np.testing.assert_allclose(
        _np(paddle.sequence_pool(xt, lt, "average")).reshape(-1), [1.5, 3.0])
    np.testing.assert_allclose(
        _np(paddle.sequence_pool(xt, lt, "max")).reshape(-1), [2.0, 3.0])
    np.testing.assert_allclose(
        _np(paddle.sequence_last_step(xt, lt)).reshape(-1), [2.0, 3.0])
    np.testing.assert_allclose(
        _np(paddle.sequence_first_step(xt, lt)).reshape(-1), [1.0, 3.0])

    sm = _np(paddle.sequence_softmax(paddle.to_tensor(x), lt))
    e = np.exp([1.0, 2.0])
    np.testing.assert_allclose(sm[0], list(e / e.sum()) + [0.0], rtol=1e-6)
    np.testing.assert_allclose(sm[1], [1.0, 0.0, 0.0], atol=1e-7)

    rv = _np(paddle.sequence_reverse(paddle.to_tensor(x), lt))
    np.testing.assert_allclose(rv[0], [2.0, 1.0, 9.0])
    np.testing.assert_allclose(rv[1], [3.0, 9.0, 9.0])


def test_sequence_pad_unpad_expand_roundtrip():
    flat = np.arange(10, dtype=np.float32).reshape(5, 2)
    lens = np.array([3, 2], np.int64)
    padded, out_lens = paddle.sequence_pad(paddle.to_tensor(flat), lens,
                                           pad_value=-1.0)
    assert list(padded.shape) == [2, 3, 2]
    np.testing.assert_allclose(_np(padded)[1, 2], [-1.0, -1.0])
    back = paddle.sequence_unpad(padded, out_lens)
    np.testing.assert_allclose(_np(back), flat)

    ex = paddle.sequence_expand(paddle.to_tensor(flat[:2]),
                                np.array([2, 1], np.int64))
    np.testing.assert_allclose(_np(ex), flat[[0, 0, 1]])


def test_sequence_conv_identity_window():
    rng = np.random.RandomState(4)
    x = rng.randn(2, 4, 3).astype(np.float32)
    # context_length=1, context_start=0 with identity weight = masked copy
    w = np.eye(3, dtype=np.float32)
    lens = np.array([4, 2], np.int64)
    out = _np(paddle.sequence_conv(paddle.to_tensor(x), paddle.to_tensor(w),
                                   paddle.to_tensor(lens),
                                   context_length=1, context_start=0))
    np.testing.assert_allclose(out[0], x[0], rtol=1e-6)
    np.testing.assert_allclose(out[1, :2], x[1, :2], rtol=1e-6)
    np.testing.assert_allclose(out[1, 2:], 0.0)


def test_sequence_concat_enumerate_expand_as():
    """New family members (sequence_{concat,enumerate,expand_as}_op.h)."""
    x1 = paddle.to_tensor(np.array([[1, 2, 0], [3, 0, 0]], np.float32))
    l1 = paddle.to_tensor(np.array([2, 1]))
    x2 = paddle.to_tensor(np.array([[5, 0], [6, 7]], np.float32))
    l2 = paddle.to_tensor(np.array([1, 2]))
    out, ol = paddle.sequence_concat([x1, x2], [l1, l2])
    np.testing.assert_allclose(np.asarray(out._data),
                               [[1, 2, 5, 0, 0], [3, 6, 7, 0, 0]])
    np.testing.assert_array_equal(np.asarray(ol._data), [3, 3])

    e = paddle.sequence_enumerate(
        paddle.to_tensor(np.array([[1, 2, 3, 0]], np.int64)),
        paddle.to_tensor(np.array([3])), 2)
    np.testing.assert_array_equal(
        np.asarray(e._data)[0], [[1, 2], [2, 3], [3, 0], [0, 0]])

    ea = paddle.sequence_expand_as(
        paddle.to_tensor(np.array([[9.0], [8.0]], np.float32)),
        paddle.to_tensor(np.array([2, 3])))
    np.testing.assert_allclose(np.asarray(ea._data)[..., 0],
                               [[9, 9, 0], [8, 8, 8]])


def test_sequence_reshape_scatter_slice():
    r, rl = paddle.sequence_reshape(
        paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(2, 3, 2)),
        paddle.to_tensor(np.array([2, 3])), 1)
    assert list(r.shape) == [2, 6, 1]
    np.testing.assert_array_equal(np.asarray(rl._data), [4, 6])

    s = paddle.sequence_scatter(
        paddle.to_tensor(np.zeros(6, np.float32)),
        paddle.to_tensor(np.array([[1, 3], [2, 0]], np.int64)),
        paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)),
        paddle.to_tensor(np.array([2, 1])))
    np.testing.assert_allclose(np.asarray(s._data), [0, 1, 3, 2, 0, 0])

    sl, sll = paddle.sequence_slice(
        paddle.to_tensor(np.array([[1, 2, 3, 4], [5, 6, 7, 8]], np.float32)),
        paddle.to_tensor(np.array([4, 4])),
        paddle.to_tensor(np.array([1, 0])),
        paddle.to_tensor(np.array([2, 3])))
    np.testing.assert_allclose(np.asarray(sl._data),
                               [[2, 3, 0, 0], [5, 6, 7, 0]])
    np.testing.assert_array_equal(np.asarray(sll._data), [2, 3])
