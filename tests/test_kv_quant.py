"""Int8 KV pools + quantized collectives: the quality-gate contract.

Every previous generation perf path shipped under bitwise token
identity vs the fp32 oracle.  int8 storage is lossy by construction, so
the contract splits in two (docs/GENERATION.md "Quantized KV and
collectives"):

- vs the fp32 oracle: the QUALITY GATE — bounded max-logit drift and
  >= 99% greedy-token agreement on seeded workloads
  (generation/quality.py);
- int8-vs-int8: strict TOKEN IDENTITY across every engine path —
  host/device backends, both pool layouts, eager/fused/ragged,
  kernel-vs-reference, preemption, prefix warm starts, live migration,
  and the forced 4-device CPU mesh — quantization changes values ONCE
  (at the write), never per path.

Plus the storage facts (int8 halves bf16 pool bytes at equal page
count, scales ride COW copies and exports) and the typed
heterogeneous-fleet refusal (KVQuantMismatchError).
"""
import numpy as np
import pytest

import paddle_tpu.generation as gen
from paddle_tpu.generation.kv_cache import (DeviceKVPool,
                                            KVQuantMismatchError,
                                            PagedKVCache)
from paddle_tpu.generation.quantized_kv import (dequantize_int8,
                                                quantize_int8)

L, H, D, PS = 2, 2, 8, 4
VOCAB = 64


@pytest.fixture(scope="module")
def model():
    return gen.TinyCausalLM(vocab_size=VOCAB, num_layers=L, num_heads=H,
                            head_dim=D, max_positions=512, seed=0)


@pytest.fixture(scope="module")
def mesh_model():
    # 4-way head sharding needs heads % 4 == 0
    return gen.TinyCausalLM(vocab_size=VOCAB, num_layers=L, num_heads=4,
                            head_dim=D, max_positions=512, seed=0)


PROMPTS = [[1, 2, 3, 4, 5, 6, 7], [9, 8, 7, 6, 5],
           [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]]


def run_engine(model, prompts=PROMPTS, new_tokens=10, stochastic=False,
               **cfg):
    cfg.setdefault("max_decode_slots", 4)
    cfg.setdefault("num_pages", 64)
    cfg.setdefault("page_size", PS)
    eng = gen.GenerationEngine(model, gen.GenerationConfig(**cfg),
                               start=False)
    try:
        handles = []
        for i, p in enumerate(prompts):
            sampling = (gen.SamplingParams(temperature=0.8, top_k=8,
                                           seed=100 + i)
                        if stochastic and i % 2 else gen.SamplingParams())
            handles.append(eng.submit(p, max_new_tokens=new_tokens,
                                      sampling=sampling))
        eng.run_until_idle()
        out = [h.result(timeout=30).token_ids for h in handles]
        snap = eng.stats()
    finally:
        eng.shutdown()
    return out, snap


def fill_cache(cache, seq="s", n=10, seed=0):
    rng = np.random.default_rng(seed)
    cache.allocate(seq)
    k = rng.standard_normal((cache.num_layers, n, cache.num_heads,
                             cache.head_dim)).astype(np.float32)
    v = rng.standard_normal((cache.num_layers, n, cache.num_heads,
                             cache.head_dim)).astype(np.float32)
    cache.append_prefill(seq, k, v)
    return k, v


# --------------------------- storage facts ---------------------------

def test_int8_pool_halves_bytes_vs_bf16():
    """The acceptance arithmetic: int8 pools (scales included) hold the
    same pages in ~half the bf16 bytes, for the host backend and both
    device layouts."""
    def pool_bytes(cache):
        b = cache.k_pool.nbytes + cache.v_pool.nbytes
        if cache.quantized:
            b += cache.k_scale.nbytes + cache.v_scale.nbytes
        return b

    for build in (
        lambda dt: PagedKVCache(L, H, D, num_pages=32, page_size=PS,
                                dtype=dt),
        lambda dt: DeviceKVPool(L, H, D, num_pages=32, page_size=PS,
                                dtype=dt),
        lambda dt: DeviceKVPool(L, H, D, num_pages=32, page_size=PS,
                                dtype=dt, pool_layout="kernel"),
    ):
        q = build(np.int8)
        b16 = build("bfloat16")
        assert q.dtype.itemsize == 1 and q.quantized
        ratio = pool_bytes(q) / pool_bytes(b16)
        assert ratio <= 0.6, f"int8 pool is {ratio:.2f}x bf16 bytes"


def test_quantized_write_matches_fake_quant():
    """A one-span page write is EXACTLY the single-rounding fake-quant
    of the payload against the page's per-head abs-max — the
    paddle_tpu.quant.quant_dequant grid (the same machinery the
    quality harness reuses)."""
    import jax.numpy as jnp

    from paddle_tpu.quant import quant_dequant

    cache = PagedKVCache(L, H, D, num_pages=8, page_size=PS,
                         dtype=np.int8)
    k, v = fill_cache(cache, n=PS)         # exactly one full page
    stored = dequantize_int8(cache.k_pool[:, cache.page_table("s")[0]],
                             cache.k_scale[:, cache.page_table("s")[0]]
                             [:, None, :, None])
    scale = np.max(np.abs(k[:, :PS]), axis=(1, 3))[:, None, :, None]
    ideal = np.asarray(quant_dequant(jnp.asarray(k[:, :PS]),
                                     jnp.asarray(scale)))
    # quant_dequant computes q * scale / 127, our dequant
    # q * (scale * 1/127): same grid, ulp-different expression order
    np.testing.assert_allclose(stored, ideal, rtol=0, atol=1e-6)


def test_write_roundtrip_error_bound():
    """gather_prefix hands back dequantized rows within half an LSB of
    the page grid (scale / 127 / 2) of the original payload."""
    for cache in (
        PagedKVCache(L, H, D, num_pages=16, page_size=PS,
                     dtype=np.int8),
        DeviceKVPool(L, H, D, num_pages=16, page_size=PS,
                     dtype=np.int8, pool_layout="kernel"),
    ):
        k, _ = fill_cache(cache, n=11)
        got = np.asarray(cache.gather_prefix("s", 0, 11)[0])
        bound = np.max(np.abs(k[0])) / 127 * 0.51 + 1e-7
        assert np.max(np.abs(got - k[0])) <= bound


def test_page_scale_resets_on_reuse():
    """A freed page's scale must not poison the next owner: after a
    large-magnitude sequence frees its pages, a small-magnitude
    sequence quantizes on a FRESH grid (pool history cannot change
    bytes — the determinism int8-vs-int8 identity rests on)."""
    for cache in (
        PagedKVCache(L, H, D, num_pages=4, page_size=PS, dtype=np.int8),
        DeviceKVPool(L, H, D, num_pages=4, page_size=PS, dtype=np.int8),
    ):
        rng = np.random.default_rng(0)
        big = rng.standard_normal((L, PS, H, D)).astype(np.float32) * 100
        cache.allocate("big")
        cache.append_prefill("big", big, big)
        cache.free("big")
        small = rng.standard_normal((L, PS, H, D)).astype(np.float32)
        cache.allocate("small")
        cache.append_prefill("small", small, small)
        got = np.asarray(cache.gather_prefix("small", 0, PS)[0])
        bound = np.max(np.abs(small[0])) / 127 * 0.51 + 1e-7
        assert np.max(np.abs(got - small[0])) <= bound
        # and the scale rows themselves reflect the SMALL payload
        page = cache.page_table("small")[0]
        assert np.max(cache.k_scale[:, page]) <= np.max(np.abs(small))


def test_host_device_quantize_bitwise():
    """The host numpy transform and the in-trace device transform
    produce bit-identical int8 pools and scales (round-half-to-even in
    both) — both layouts."""
    caches = [
        PagedKVCache(L, H, D, num_pages=16, page_size=PS,
                     dtype=np.int8),
        DeviceKVPool(L, H, D, num_pages=16, page_size=PS,
                     dtype=np.int8),
        DeviceKVPool(L, H, D, num_pages=16, page_size=PS,
                     dtype=np.int8, pool_layout="kernel"),
    ]
    rng = np.random.default_rng(3)
    extra_k = rng.standard_normal((L, H, D)).astype(np.float32)
    extra_v = rng.standard_normal((L, H, D)).astype(np.float32)
    for c in caches:
        fill_cache(c, n=10, seed=7)
        c.append("s", extra_k, extra_v)     # decode-style append
    ref = caches[0]
    for c in caches[1:]:
        assert np.array_equal(ref.k_pool, c.k_pool)
        assert np.array_equal(ref.v_pool, c.v_pool)
        assert np.array_equal(ref.k_scale, c.k_scale)
        assert np.array_equal(ref.v_scale, c.v_scale)


# ----------------------- export / import / COW -----------------------

def test_export_import_bitwise_roundtrip():
    """int8 pages + scales roundtrip bitwise through the canonical
    export payload, across backend/layout combinations."""
    builders = [
        lambda: PagedKVCache(L, H, D, num_pages=16, page_size=PS,
                             dtype=np.int8),
        lambda: DeviceKVPool(L, H, D, num_pages=16, page_size=PS,
                             dtype=np.int8),
        lambda: DeviceKVPool(L, H, D, num_pages=16, page_size=PS,
                             dtype=np.int8, pool_layout="kernel"),
    ]
    for src_build in builders:
        src = src_build()
        fill_cache(src, n=9, seed=5)
        payload = src.export_pages(src.page_table("s"))
        assert len(payload) == 4 and payload[0].dtype == np.int8
        for dst_build in builders:
            dst = dst_build()
            pages = dst.import_pages(*payload)
            again = dst.export_pages(pages)
            for a, b in zip(payload, again):
                assert np.array_equal(np.asarray(a), np.asarray(b))


def test_export_import_bitwise_mesh():
    """A head-sharded int8 pool exports the same canonical payload as
    an unsharded one, and a donated import re-installs it bitwise with
    the sharding (and scale sharding) preserved."""
    from paddle_tpu.parallel import tp_mesh

    mesh = tp_mesh(4)
    plain = DeviceKVPool(L, 4, D, num_pages=16, page_size=PS,
                         dtype=np.int8)
    sharded = DeviceKVPool(L, 4, D, num_pages=16, page_size=PS,
                           dtype=np.int8, mesh=mesh)
    for c in (plain, sharded):
        fill_cache(c, n=9, seed=5)
    pp = plain.export_pages(plain.page_table("s"))
    sp = sharded.export_pages(sharded.page_table("s"))
    for a, b in zip(pp, sp):
        assert np.array_equal(a, b)
    pages = sharded.import_pages(*pp)
    again = sharded.export_pages(pages)
    for a, b in zip(pp, again):
        assert np.array_equal(a, b)
    # the installed scale arrays keep their NamedSharding
    assert sharded._ks[0].sharding == sharded.scale_sharding


def test_import_quant_mismatch_typed():
    """The heterogeneous-fleet boundary is typed and loud: int8 bytes
    into a float pool, float bytes into an int8 pool, and scale-less
    int8 payloads all raise KVQuantMismatchError (a ValueError, so the
    serving fallbacks stay graceful)."""
    q = PagedKVCache(L, H, D, num_pages=16, page_size=PS, dtype=np.int8)
    f = PagedKVCache(L, H, D, num_pages=16, page_size=PS,
                     dtype="bfloat16")
    fill_cache(q, n=6, seed=1)
    fill_cache(f, n=6, seed=1)
    qpay = q.export_pages(q.page_table("s"))
    fpay = f.export_pages(f.page_table("s"))
    with pytest.raises(KVQuantMismatchError):
        f.import_pages(*qpay)               # int8 -> bf16 pool
    with pytest.raises(KVQuantMismatchError):
        q.import_pages(*fpay)               # bf16 -> int8 pool
    with pytest.raises(KVQuantMismatchError):
        q.import_pages(qpay[0], qpay[1])    # int8 without its grid
    assert issubclass(KVQuantMismatchError, ValueError)


def test_heterogeneous_fleet_adoption_degrades_typed(model):
    """Engine level: an int8 replica's exported state offered to a
    bf16 replica is refused (False / 0), never installed — the
    cold-resubmit / skip-adoption ladders handle the heterogeneous
    fleet."""
    src = gen.GenerationEngine(model, gen.GenerationConfig(
        max_decode_slots=2, num_pages=64, page_size=PS,
        kv_backend="device", kv_dtype="int8", prefill_chunk_tokens=4,
        prefix_cache=True), start=False)
    dst = gen.GenerationEngine(model, gen.GenerationConfig(
        max_decode_slots=2, num_pages=64, page_size=PS,
        kv_backend="device", kv_dtype="bfloat16",
        prefill_chunk_tokens=4, prefix_cache=True), start=False)
    try:
        h = src.submit(PROMPTS[0], max_new_tokens=6)
        for _ in range(40):
            if h.done():
                break
            src.step()
        h.result(timeout=5)
        payload = src.export_prefix_pages(PROMPTS[0])
        assert payload is not None and "k_scale" in payload
        assert dst.import_prefix_pages(payload) == 0
        # live-migration snapshot refused the same way
        h2 = src.submit(PROMPTS[2], max_new_tokens=8)
        for _ in range(6):
            src.step()
        cold, live = src.evacuate_for_migration()
        assert live, "expected a live decode-phase snapshot"
        assert dst.import_sequence(live[0]) is False
        live[0]["future"].set_exception(RuntimeError("test drain"))
        for req, _ in cold:
            req.future.set_exception(RuntimeError("test drain"))
    finally:
        src.shutdown()
        dst.shutdown()


def test_cow_privatization_copies_scales(model):
    """Prefix-cache COW at int8: the private copy carries the donor's
    bytes AND scale rows; the donor page stays pinned bitwise; and the
    refcount-leak invariant holds (drain + flush == all free)."""
    eng = gen.GenerationEngine(model, gen.GenerationConfig(
        max_decode_slots=4, num_pages=64, page_size=PS,
        kv_backend="device", kv_dtype="int8", prefill_chunk_tokens=4,
        prefix_cache=True), start=False)
    try:
        cache = eng.cache
        warm = [5] * (2 * PS + 2)           # full shared pages + tail
        h1 = eng.submit(warm, max_new_tokens=4)
        eng.run_until_idle()
        h1.result(timeout=10)
        donor_pages = cache.match_prefix(warm + [9])[0]
        assert donor_pages
        donor_k = cache.k_pool[:, list(donor_pages)].copy()
        donor_ks = cache.k_scale[:, list(donor_pages)].copy()
        cow_before = cache._cow_copies
        # same prefix, divergent suffix -> aliases pages, COWs the tail
        h2 = eng.submit(warm[:2 * PS + 1] + [9, 9, 9],
                        max_new_tokens=4)
        eng.run_until_idle()
        h2.result(timeout=10)
        assert cache._cow_copies + \
            eng.metrics.snapshot().get("generation.cow_copies", 0) \
            >= cow_before   # COW path exercised (counter drained)
        # donor pages: bytes and scales pinned bitwise
        assert np.array_equal(cache.k_pool[:, list(donor_pages)],
                              donor_k)
        assert np.array_equal(cache.k_scale[:, list(donor_pages)],
                              donor_ks)
        # refcount-leak invariant at int8
        assert cache.pages_in_use > 0
        cache.flush_prefix_cache()
        assert cache.num_free_pages == cache.num_pages
    finally:
        eng.shutdown()


# ----------------------- int8-vs-int8 identity -----------------------

def test_int8_host_vs_device_identity(model):
    base, _ = run_engine(model, kv_dtype="int8", kv_backend="host",
                         stochastic=True)
    for layout in ("token", "kernel"):
        out, _ = run_engine(model, kv_dtype="int8", kv_backend="device",
                            pool_layout=layout, stochastic=True)
        assert out == base


def test_int8_fused_vs_eager_identity(model):
    base, _ = run_engine(model, kv_dtype="int8", kv_backend="device",
                         stochastic=True)
    out, snap = run_engine(model, kv_dtype="int8", kv_backend="device",
                           decode="fused", stochastic=True)
    assert out == base
    assert snap.get("generation.kv_quant_dtype") == "int8"


def test_int8_ragged_vs_eager_identity(model):
    base, _ = run_engine(model, kv_dtype="int8", kv_backend="device",
                         prefill_chunk_tokens=4, stochastic=True)
    out, _ = run_engine(model, kv_dtype="int8", kv_backend="device",
                        step_mode="ragged", prefill_chunk_tokens=4,
                        stochastic=True)
    assert out == base


def test_int8_kernel_vs_reference_identity(model):
    """In-kernel dequant (interpret mode on CPU) reproduces the
    reference path token for token — decode, chunk, and ragged
    kernels, both layouts."""
    for layout in ("token", "kernel"):
        ref, _ = run_engine(model, kv_dtype="int8", kv_backend="device",
                            step_mode="ragged", prefill_chunk_tokens=4,
                            pool_layout=layout, use_kernel=False)
        ker, _ = run_engine(model, kv_dtype="int8", kv_backend="device",
                            step_mode="ragged", prefill_chunk_tokens=4,
                            pool_layout=layout, use_kernel=True)
        assert ker == ref
    ref, _ = run_engine(model, kv_dtype="int8", kv_backend="device",
                        decode="fused", use_kernel=False)
    ker, _ = run_engine(model, kv_dtype="int8", kv_backend="device",
                        decode="fused", use_kernel=True)
    assert ker == ref


def test_int8_preemption_token_identity(model):
    """Forced preemption (tight pool) replays re-prefill through the
    same quantized write history — tokens identical to the roomy
    run."""
    roomy, _ = run_engine(model, num_pages=64, kv_dtype="int8",
                          kv_backend="device", stochastic=True)
    tight, snap = run_engine(model, num_pages=11, kv_dtype="int8",
                             kv_backend="device", stochastic=True)
    assert snap.get("generation.preempted_total", 0) > 0, \
        "the tight pool was expected to force preemption"
    assert tight == roomy


def test_int8_prefix_warm_vs_cold_identity(model):
    """Warm starts at int8: the suffix run after aliasing cached int8
    pages generates the same tokens as the cold run."""
    prompt = [5] * (2 * PS) + [1, 2, 3]
    cold, _ = run_engine(model, prompts=[prompt], kv_dtype="int8",
                         kv_backend="device", prefill_chunk_tokens=4,
                         prefix_cache=False)
    eng = gen.GenerationEngine(model, gen.GenerationConfig(
        max_decode_slots=4, num_pages=64, page_size=PS,
        kv_backend="device", kv_dtype="int8", prefill_chunk_tokens=4,
        prefix_cache=True), start=False)
    try:
        h1 = eng.submit(prompt, max_new_tokens=10)
        eng.run_until_idle()
        first = h1.result(timeout=10).token_ids
        h2 = eng.submit(prompt, max_new_tokens=10)
        eng.run_until_idle()
        warm = h2.result(timeout=10).token_ids
        assert h2.prefix_hit_tokens and h2.prefix_hit_tokens > 0
    finally:
        eng.shutdown()
    assert first == cold[0]
    assert warm == cold[0]


def test_int8_mesh_token_identity(mesh_model):
    """The forced 4-device CPU mesh (ragged + shard_map'd kernels,
    scales head-sharded) is token-identical to the single-chip int8
    eager oracle."""
    from paddle_tpu.parallel import tp_mesh

    base, _ = run_engine(mesh_model, kv_dtype="int8",
                         kv_backend="device", stochastic=True)
    out, snap = run_engine(mesh_model, kv_dtype="int8",
                           kv_backend="device", mesh=tp_mesh(4),
                           step_mode="ragged", prefill_chunk_tokens=4,
                           use_kernel=True, stochastic=True)
    assert out == base
    assert snap.get("generation.mesh_devices") == 4


def test_int8_live_migration_resume(model):
    """Mid-stream drain at int8: the sibling imports page bytes +
    scales and RESUMES — the stitched stream equals the uninterrupted
    run."""
    cfg = dict(max_decode_slots=2, num_pages=64, page_size=PS,
               kv_backend="device", kv_dtype="int8")
    full, _ = run_engine(model, prompts=[PROMPTS[0]], new_tokens=12,
                         **cfg)
    a = gen.GenerationEngine(model, gen.GenerationConfig(**cfg),
                             start=False)
    b = gen.GenerationEngine(model, gen.GenerationConfig(**cfg),
                             start=False)
    try:
        h = a.submit(PROMPTS[0], max_new_tokens=12)
        for _ in range(5):
            a.step()
        cold, live = a.evacuate_for_migration()
        assert not cold and len(live) == 1
        assert live[0]["k_scale"] is not None
        assert b.import_sequence(live[0])
        b.run_until_idle()
        assert h.result(timeout=10).token_ids == full[0]
    finally:
        a.shutdown()
        b.shutdown()


# --------------------------- quality gate ----------------------------

def test_quality_gate_drift_and_agreement(model):
    """The acceptance contract vs the fp32 oracle: >= 99% greedy-token
    agreement and bounded max-logit drift that tracks the idealized
    single-rounding fake-quant floor."""
    from paddle_tpu.generation.quality import kv_quality_report

    mk = lambda **kw: gen.GenerationConfig(  # noqa: E731
        max_decode_slots=4, num_pages=64, page_size=PS,
        kv_backend="device", **kw)
    rep = kv_quality_report(model, mk(), mk(kv_dtype="int8"),
                            max_new_tokens=12)
    assert rep["agreement"] >= 0.99, rep
    assert rep["max_logit_drift"] < 0.25, rep
    # the engine write path must track the single-rounding ideal: a
    # runaway-requantization regression would blow this envelope
    assert rep["max_logit_drift"] <= \
        rep["ideal_fake_quant_drift"] * 4 + 0.05, rep


# ------------------------ quantized collectives ----------------------

def test_quantized_ring_allreduce_exact_enough():
    import jax

    from paddle_tpu.parallel import tp_mesh
    from paddle_tpu.parallel.quantized_allreduce import (
        quantized_matmul_allreduce)

    rng = np.random.default_rng(0)
    a = rng.standard_normal((6, 16)).astype(np.float32)
    w = rng.standard_normal((16, 12)).astype(np.float32)
    exact = a @ w
    for n in (2, 4):
        mesh = tp_mesh(n)
        qmm = jax.jit(quantized_matmul_allreduce(
            mesh, mesh.axis_names[0]))
        out = np.asarray(qmm(a, w))
        rel = np.max(np.abs(out - exact)) / np.max(np.abs(exact))
        assert rel < 0.05, (n, rel)
        # deterministic: the ring order is fixed, re-running is bitwise
        assert np.array_equal(out, np.asarray(qmm(a, w)))


def test_quantized_collective_bytes_estimate():
    from paddle_tpu.generation.fused import _collective_bytes_estimate

    fp32 = _collective_bytes_estimate(2, 16, 64, 4)
    q = _collective_bytes_estimate(2, 16, 64, 4, quantized=True)
    assert fp32 / q >= 3.0, (fp32, q)
    assert _collective_bytes_estimate(2, 16, 64, 1, quantized=True) == 0


def test_quantized_collectives_engine(mesh_model):
    """The 4-device CPU mesh cell: the flag cuts
    collective_bytes_per_step >= 3x, stamps collective_quantized=1,
    and passes the same token-agreement gate vs its fp32-collective
    sibling."""
    from paddle_tpu.parallel import tp_mesh

    mesh = tp_mesh(4)
    kw = dict(kv_dtype="int8", kv_backend="device", mesh=mesh,
              step_mode="ragged", prefill_chunk_tokens=4,
              use_kernel=True)
    base, snap_fp = run_engine(mesh_model, **kw)
    quant, snap_q = run_engine(mesh_model, quantized_collectives=True,
                               **kw)
    assert snap_fp.get("generation.collective_quantized") == 0
    assert snap_q.get("generation.collective_quantized") == 1
    fp_bytes = snap_fp.get("generation.collective_bytes_per_step")
    q_bytes = snap_q.get("generation.collective_bytes_per_step")
    assert fp_bytes / q_bytes >= 3.0, (fp_bytes, q_bytes)
    total = sum(len(t) for t in base)
    agree = sum(x == y for tb, tq in zip(base, quant)
                for x, y in zip(tb, tq))
    assert agree / total >= 0.99, (agree, total, base, quant)


def test_quantized_collectives_inert_without_mesh(model):
    """The flag without collectives to quantize is visible as a stats
    fact, not a silent pretend-on."""
    _, snap = run_engine(model, kv_dtype="int8", kv_backend="device",
                         quantized_collectives=True)
    assert snap.get("generation.collective_quantized") == 0


# ------------------------------ metrics ------------------------------

def test_kv_quant_metrics_and_stats(model):
    out, snap = run_engine(model, kv_dtype="int8", kv_backend="device",
                           decode="fused")
    assert snap.get("generation.kv_quant_dtype") == "int8"
    scale_bytes = snap.get("generation.kv_scale_bytes", 0)
    assert scale_bytes > 0
    # folded: scales are a subset of the total bytes in flight
    assert snap.get("generation.kv_bytes_moved", 0) >= scale_bytes
    assert snap.get("cache.kv_dtype") == "int8"
    # fp32 engines stamp their dtype too (schema-complete snapshots)
    _, snap32 = run_engine(model, kv_backend="device")
    assert snap32.get("generation.kv_quant_dtype") == "float32"


def test_config_accepts_dtype_names():
    cfg = gen.GenerationConfig(kv_dtype="int8")
    assert cfg.kv_dtype == np.dtype(np.int8)
    assert gen.GenerationConfig().kv_dtype == np.dtype(np.float32)


def test_int8_pool_without_scales_fails_loudly():
    """An int8 pool reaching attention without its scale arrays must
    fail typed instead of mis-decoding raw codes as values — the same
    silent-corruption class KVQuantMismatchError guards at the import
    boundary, caught at the reference gather and the kernel wrappers."""
    import jax.numpy as jnp

    from paddle_tpu.generation import decode_attention as da
    from paddle_tpu.ops.pallas import paged_attention as pk

    q = jnp.zeros((1, H, D), jnp.float32)
    pool = jnp.zeros((4, PS, H, D), jnp.int8)
    fpool = jnp.zeros((4, PS, H, D), jnp.float32)
    sc = jnp.ones((4, H), jnp.float32)
    pt = jnp.zeros((1, 2), jnp.int32)
    lens = jnp.ones((1,), jnp.int32)
    with pytest.raises(ValueError, match="scale"):
        da.paged_decode_attention_reference(q, pool, pool, pt, lens)
    with pytest.raises(ValueError, match="scale"):
        pk.paged_decode_attention_kernel(q, pool, pool, pt, lens,
                                         scale=1.0, interpret=True)
    # the adjacent misuses fail just as loudly: half-threaded scales,
    # and scales alongside a non-int8 pool (silent scale/127 corruption)
    with pytest.raises(ValueError, match="together"):
        pk.paged_decode_attention_kernel(q, pool, pool, pt, lens,
                                         scale=1.0, interpret=True,
                                         k_scale=sc)
    with pytest.raises(ValueError, match="int8 pools only"):
        pk.paged_decode_attention_kernel(q, fpool, fpool, pt, lens,
                                         scale=1.0, interpret=True,
                                         k_scale=sc, v_scale=sc)
    with pytest.raises(ValueError, match="int8 pools only"):
        da.paged_decode_attention_reference(q, fpool, fpool, pt, lens,
                                            k_scale=sc, v_scale=sc)
