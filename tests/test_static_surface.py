"""Static long-tail surface: the paddle.static/{nn} exports added for
reference parity — norm/conv/prelu emitters over the eager bridge, the
sequence family, auc, scope/place helpers, var IO and program
(de)serialization (python/paddle/static/__init__.py export list).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _run(main, startup, feed, fetch):
    exe = static.Executor()
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=fetch)


def test_norm_conv_prelu_emitters_match_eager():
    rng = np.random.RandomState(0)
    xv = rng.rand(2, 4, 6, 6).astype(np.float32)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 4, 6, 6])
        gn = static.nn.group_norm(x, groups=2, param_attr=False,
                                  bias_attr=False)
        inorm = static.nn.instance_norm(x, param_attr=False,
                                        bias_attr=False)
        pr = static.nn.prelu(x, mode="all")
        loss = static.nn.mean(gn + inorm + pr)
    out, = _run(main, startup, {"x": xv}, [loss])
    paddle.disable_static()
    import paddle_tpu.nn.functional as F

    t = paddle.to_tensor(xv)
    want = float(np.asarray(paddle.mean(
        F.group_norm(t, 2) + F.instance_norm(t)
        + F.prelu(t, paddle.to_tensor(np.full((1,), 0.25, np.float32)))
    )._data))
    paddle.enable_static()
    np.testing.assert_allclose(float(out), want, rtol=1e-5)


def test_static_sequence_family():
    rng = np.random.RandomState(1)
    xv = rng.rand(2, 4, 3).astype(np.float32)
    lens = np.array([3, 2], np.int64)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 4, 3])
        ln = static.data("len", [2], dtype="int64")
        pool = static.nn.sequence_pool(x, ln, "sum")
        rev = static.nn.sequence_reverse(x, ln)
        sm = static.nn.sequence_softmax(x, ln)
        first = static.nn.sequence_first_step(x, ln)
    pool_v, rev_v, sm_v, first_v = _run(
        main, startup, {"x": xv, "len": lens}, [pool, rev, sm, first])
    # oracles
    want_pool = np.stack([xv[0, :3].sum(0), xv[1, :2].sum(0)])
    np.testing.assert_allclose(pool_v, want_pool, rtol=1e-5)
    np.testing.assert_allclose(rev_v[0, :3], xv[0, :3][::-1], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(sm_v)[0, :3].sum(0),
                               np.ones(3), rtol=1e-5)
    np.testing.assert_allclose(first_v, xv[:, 0], rtol=1e-6)


def test_static_sequence_pad_enumerate_slice():
    main, startup = static.Program(), static.Program()
    flat = np.arange(10, dtype=np.float32).reshape(5, 2)
    lens = np.array([3, 2], np.int64)
    with static.program_guard(main, startup):
        x = static.data("x", [5, 2])
        ln = static.data("len", [2], dtype="int64")
        padded = static.nn.sequence_pad(x, ln, maxlen=3)
        ids = static.data("ids", [2, 3], dtype="int64")
        enum = static.nn.sequence_enumerate(ids, ln, 2)
    out = _run(main, startup,
               {"x": flat, "len": lens,
                "ids": np.array([[1, 2, 3], [4, 5, 0]], np.int64)},
               [padded[0], padded[1], enum])
    pad_v, len_v, enum_v = out
    np.testing.assert_allclose(pad_v[0], flat[:3], rtol=1e-6)
    np.testing.assert_allclose(pad_v[1, :2], flat[3:5], rtol=1e-6)
    np.testing.assert_array_equal(len_v, lens)
    np.testing.assert_array_equal(enum_v[0, 0], [1, 2])


def test_auc_op():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        pred = static.data("pred", [6, 2])
        lbl = static.data("lbl", [6, 1], dtype="int64")
        auc_val, batch_auc = static.auc(pred, lbl, num_thresholds=200)
    scores = np.array([0.1, 0.2, 0.8, 0.9, 0.3, 0.7], np.float32)
    preds = np.stack([1 - scores, scores], axis=1)
    labels = np.array([[0], [0], [1], [1], [0], [1]], np.int64)
    v, _ = _run(main, startup, {"pred": preds, "lbl": labels},
                [auc_val, batch_auc])
    np.testing.assert_allclose(float(v), 1.0, atol=0.02)  # separable


def test_var_io_and_program_state(tmp_path):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 3])
        y = static.nn.fc(x, 4)
        loss = static.nn.mean(y)
    exe = static.Executor()
    exe.run(startup)
    xv = np.ones((2, 3), np.float32)
    before, = exe.run(main, feed={"x": xv}, fetch_list=[loss])
    saved = static.save_vars(exe, str(tmp_path / "vars"), main)
    assert saved
    state = static.load_program_state(str(tmp_path / "vars"))
    assert set(state) == set(saved)
    # clobber the scope then restore
    for n in saved:
        static.global_scope().set(n, np.zeros_like(state[n]))
    zero, = exe.run(main, feed={"x": xv}, fetch_list=[loss])
    assert abs(float(zero)) < 1e-6
    static.set_program_state(main, state)
    after, = exe.run(main, feed={"x": xv}, fetch_list=[loss])
    np.testing.assert_allclose(float(after), float(before), rtol=1e-6)


def test_program_serialization_roundtrip():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 3])
        y = static.nn.fc(x, 4)
    blob = static.serialize_program([x], [y], program=main)
    prog2 = static.deserialize_program(blob)
    assert isinstance(blob, bytes) and prog2.global_block().ops
    pers = static.serialize_persistables([x], [y], program=main)
    assert static.deserialize_persistables(main, pers) >= 0


def test_scope_and_places():
    assert len(static.cpu_places(3)) == 3
    sc = static.Scope()
    sc.set("v", np.ones(2))
    with static.scope_guard(sc):
        assert static.global_scope() is sc
    assert static.global_scope() is not sc
    with static.device_guard("cpu"):
        pass
    g = static.create_global_var([2], 1.5, "float32")
    assert g.shape == [2]
    with pytest.raises(RuntimeError):
        static.xpu_places()


def test_conv2d_act_is_applied():
    """static.nn.conv2d(act='relu') must actually rectify (it was once a
    silently-ignored parameter)."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [1, 1, 4, 4])
        y = static.nn.conv2d(x, 2, 3, padding=1, act="relu",
                             bias_attr=False)
    exe = static.Executor()
    exe.run(startup)
    out, = exe.run(main,
                   feed={"x": np.random.RandomState(0)
                         .randn(1, 1, 4, 4).astype(np.float32) * 10},
                   fetch_list=[y])
    assert (np.asarray(out) >= 0).all()
