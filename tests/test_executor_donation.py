"""Donation gating + cost_analysis on the static executor.

Reference anchors: inplace/memory passes (SURVEY §2.1 IR-pass row) are
replaced by XLA buffer donation; operators/benchmark/op_tester.cc's role
(op-level FLOPs accounting) is served by Lowered.cost_analysis().
VERDICT r2 weak #5: donating buffers XLA can't alias is worse than not
donating (warning on CPU, double HBM on TPU) — the executor must only
donate feeds whose shape/dtype can round-trip into an output.
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static


def _build_train_prog():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 8])
        y = static.nn.fc(x, 8)
        loss = static.nn.mean(y)
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        opt.minimize(loss)
    return main, startup, loss


def test_no_unusable_donation_warnings():
    paddle.seed(0)
    main, startup, loss = _build_train_prog()
    exe = static.Executor()
    exe.run(startup)
    feed = {"x": np.ones((4, 8), np.float32)}
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        first = exe.run(main, feed=feed, fetch_list=[loss])[0]
        second = exe.run(main, feed=feed, fetch_list=[loss])[0]
    # momentum actually updated params between runs
    assert not np.allclose(first, second)


def test_donation_still_happens_when_aliasable():
    """A feed whose shape/dtype matches a fetch output stays donated."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [8, 8])
        y = x * 2.0 + 1.0
    exe = static.Executor()
    feed = {"x": np.ones((8, 8), np.float32)}
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        out = exe.run(main, feed=feed, fetch_list=[y])[0]
    np.testing.assert_allclose(out, np.full((8, 8), 3.0), rtol=1e-6)
    cb = exe._get_block(main, feed, [y], None)
    assert cb._jitted is not None
    if not cb._donate_feeds:
        pytest.skip("native planner unavailable; no donation plan to keep")
    # the alias check kept the donation (jit internals probed defensively)
    info = getattr(cb._jitted, "_jit_info", None)
    if info is not None:
        assert info.donate_argnums == (0,)


def test_device_array_feeds_survive_donation():
    """Caller-owned jax.Array feeds must not be invalidated by the feed
    donation plan: the SAME jnp feed dict runs twice, bit-identically, and
    the caller's array is still readable afterwards (regression: the
    second run raised 'buffer has been deleted or donated')."""
    import jax.numpy as jnp

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [8, 8])
        y = x * 2.0 + 1.0
    exe = static.Executor()
    arr = jnp.ones((8, 8), jnp.float32)
    feed = {"x": arr}
    out1 = exe.run(main, feed=feed, fetch_list=[y])[0]
    out2 = exe.run(main, feed=feed, fetch_list=[y])[0]
    np.testing.assert_array_equal(out1, out2)
    np.testing.assert_array_equal(np.asarray(arr),
                                  np.ones((8, 8), np.float32))


def test_executor_cost_analysis_reports_flops():
    paddle.seed(0)
    main, startup, loss = _build_train_prog()
    exe = static.Executor()
    exe.run(startup)
    feed = {"x": np.ones((4, 8), np.float32)}
    ca = exe.cost_analysis(main, feed=feed, fetch_list=[loss])
    if ca is None:
        pytest.skip("backend reports no cost analysis")
    # fc fwd = 2*4*8*8 = 512 plus grads/update: well above 500
    assert ca.get("flops", 0) >= 500
