"""VERDICT r1 small items: StatRegistry gauges (monitor.h:77), leaf
register_hook (hooks.h), int64 range guard."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_stat_registry_gauges():
    from paddle_tpu.profiler import StatRegistry, stat_add, stat_get

    reg = StatRegistry.instance()
    reg.reset_all()
    stat_add("test_gauge", 5)
    stat_add("test_gauge")
    assert stat_get("test_gauge") == 6
    assert reg.stats()["test_gauge"] == 6
    reg.get_stat("test_gauge").reset()
    assert stat_get("test_gauge") == 0


def test_ps_service_increments_gauges(tmp_path):
    from paddle_tpu.distributed.ps.service import PSServer, PSClient
    from paddle_tpu.profiler import StatRegistry, stat_get
    import socket

    s = socket.socket(); s.bind(("127.0.0.1", 0))
    ep = f"127.0.0.1:{s.getsockname()[1]}"; s.close()
    StatRegistry.instance().reset_all()
    server = PSServer(ep, trainers=1)
    server.start()
    try:
        c = PSClient([ep]); c.ping()
        c.create_dense_table("w", (2,), lr=0.1)
        c.pull_dense("w"); c.pull_dense("w")
        assert stat_get("ps_server_pull_dense_count") == 2
        assert stat_get("ps_server_ping_count") >= 1
        c.close()
    finally:
        server.shutdown()


def test_register_hook_on_leaf():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    seen = []
    handle = x.register_hook(lambda g: (seen.append(g.numpy().copy()),
                                        paddle.scale(g, 2.0))[1])
    y = paddle.sum(paddle.multiply(x, x))
    y.backward()
    assert len(seen) == 1
    np.testing.assert_allclose(x.grad.numpy(), 2 * 2 * np.ones(3))  # doubled

    # removed handle: hook no longer fires
    handle.remove()
    x.clear_grad()
    y2 = paddle.sum(paddle.multiply(x, x))
    y2.backward()
    assert len(seen) == 1
    np.testing.assert_allclose(x.grad.numpy(), 2 * np.ones(3))


def test_register_hook_non_leaf_still_works():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    h = paddle.scale(x, 3.0)
    h.register_hook(lambda g: paddle.scale(g, 10.0))
    paddle.sum(h).backward()
    np.testing.assert_allclose(x.grad.numpy(), 30 * np.ones(3))


def test_int64_overflow_rejected():
    paddle.to_tensor(np.array([2**31 - 1], np.int64))  # max ok
    with pytest.raises(OverflowError, match="int32 range"):
        paddle.to_tensor(np.array([2**31], np.int64))
    with pytest.raises(OverflowError, match="int32 range"):
        paddle.to_tensor(np.array([-2**31 - 1], np.int64))


def test_flags_all_consumed():
    """Every registered FLAGS_* is consumed outside framework.py or
    carries the documented PJRT-no-op rationale (VERDICT r1 flagged dead
    flags; this enforces the set stays honest), and the wired ones have
    real behavior."""
    import os
    import glob

    import paddle_tpu as paddle
    from paddle_tpu.framework import _FLAGS

    # source-level consumption audit
    root = os.path.dirname(paddle.__file__)
    corpus = ""
    for path in glob.glob(os.path.join(root, "**", "*.py"), recursive=True):
        if path.endswith("framework.py"):
            continue
        corpus += open(path).read()
    framework_src = open(os.path.join(root, "framework.py")).read()
    documented_noop = {"FLAGS_eager_delete_tensor_gb",
                       "FLAGS_allocator_strategy"}
    side_effect_wired = {"FLAGS_seed", "FLAGS_use_bf16_matmul"}
    dead = []
    for flag in _FLAGS:
        if flag in documented_noop or flag in side_effect_wired:
            continue
        if flag not in corpus:
            dead.append(flag)
    assert not dead, f"dead flags (registered, never consumed): {dead}"
    assert "accepted no-ops" in framework_src  # rationale stays in place

    # behavioral checks for the wired ones, state restored afterwards
    from paddle_tpu.core import random as _random

    key_before = _random.get_rng_state()
    seed_before = _FLAGS.get("FLAGS_seed")
    import jax as _jax

    prec_before = _jax.config.jax_default_matmul_precision
    try:
        paddle.set_flags({"FLAGS_seed": 7})
        a = np.asarray(paddle.rand([2])._data)
        paddle.set_flags({"FLAGS_seed": 7})
        b = np.asarray(paddle.rand([2])._data)
        np.testing.assert_allclose(a, b)
        # seed 0 is a valid explicit seed (reseeds, not ignored)
        paddle.set_flags({"FLAGS_seed": 0})
        c = np.asarray(paddle.rand([2])._data)
        paddle.set_flags({"FLAGS_seed": 0})
        d = np.asarray(paddle.rand([2])._data)
        np.testing.assert_allclose(c, d)

        paddle.set_flags({"FLAGS_benchmark": True})
        out = paddle.matmul(paddle.to_tensor(np.eye(4, dtype=np.float32)),
                            paddle.to_tensor(np.eye(4, dtype=np.float32)))
        np.testing.assert_allclose(np.asarray(out._data), np.eye(4))

        paddle.set_flags({"FLAGS_use_bf16_matmul": False})
        assert _jax.config.jax_default_matmul_precision == "float32"
        paddle.set_flags({"FLAGS_use_bf16_matmul": True})
        assert _jax.config.jax_default_matmul_precision == "bfloat16"
    finally:
        paddle.set_flags({"FLAGS_benchmark": False})
        _FLAGS["FLAGS_seed"] = seed_before
        _random.set_rng_state(key_before)
        _jax.config.update("jax_default_matmul_precision", prec_before)


def test_profiler_summary_table_and_chrome_trace(tmp_path):
    """VERDICT r2 #9: EnableProfiler output parity — sorted per-event
    summary (Calls/Total/Min/Max/Ave/Ratio) + chrome-trace export."""
    import json
    import time

    from paddle_tpu import profiler

    profiler.start_profiler()
    for _ in range(3):
        with profiler.RecordEvent("op_a"):
            time.sleep(0.002)
    with profiler.RecordEvent("op_b"):
        time.sleep(0.01)
    trace_path = str(tmp_path / "trace.json")
    report = profiler.stop_profiler(sorted_key="total",
                                    profile_path=trace_path)
    lines = report.splitlines()
    assert "Profiling Report" in lines[0]
    for col in ("Calls", "Total(ms)", "Min(ms)", "Max(ms)", "Ave(ms)",
                "Ratio"):
        assert col in lines[1]
    # sorted by total: op_b (10ms) before op_a (3x2ms)
    body = [ln for ln in lines[2:] if ln.strip()]
    assert body[0].startswith("op_b") and body[1].startswith("op_a")
    assert " 3" in body[1]  # op_a call count
    # chrome trace loads as JSON with one complete event per span
    with open(trace_path) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    assert sum(e["name"] == "op_a" for e in evs) == 3
    assert sum(e["name"] == "op_b" for e in evs) == 1
    assert all(e["ph"] == "X" and e["dur"] > 0 for e in evs)
    # ratio column sums to ~1
    ratios = [float(ln.split()[-1]) for ln in body]
    assert abs(sum(ratios) - 1.0) < 1e-6


def test_profiler_sorted_key_validation():
    import pytest as _pytest

    from paddle_tpu import profiler

    with _pytest.raises(ValueError):
        profiler.summary(sorted_key="bogus")
