"""VERDICT r1 small items: StatRegistry gauges (monitor.h:77), leaf
register_hook (hooks.h), int64 range guard."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_stat_registry_gauges():
    from paddle_tpu.profiler import StatRegistry, stat_add, stat_get

    reg = StatRegistry.instance()
    reg.reset_all()
    stat_add("test_gauge", 5)
    stat_add("test_gauge")
    assert stat_get("test_gauge") == 6
    assert reg.stats()["test_gauge"] == 6
    reg.get_stat("test_gauge").reset()
    assert stat_get("test_gauge") == 0


def test_ps_service_increments_gauges(tmp_path):
    from paddle_tpu.distributed.ps.service import PSServer, PSClient
    from paddle_tpu.profiler import StatRegistry, stat_get
    import socket

    s = socket.socket(); s.bind(("127.0.0.1", 0))
    ep = f"127.0.0.1:{s.getsockname()[1]}"; s.close()
    StatRegistry.instance().reset_all()
    server = PSServer(ep, trainers=1)
    server.start()
    try:
        c = PSClient([ep]); c.ping()
        c.create_dense_table("w", (2,), lr=0.1)
        c.pull_dense("w"); c.pull_dense("w")
        assert stat_get("ps_server_pull_dense_count") == 2
        assert stat_get("ps_server_ping_count") >= 1
        c.close()
    finally:
        server.shutdown()


def test_register_hook_on_leaf():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    seen = []
    handle = x.register_hook(lambda g: (seen.append(g.numpy().copy()),
                                        paddle.scale(g, 2.0))[1])
    y = paddle.sum(paddle.multiply(x, x))
    y.backward()
    assert len(seen) == 1
    np.testing.assert_allclose(x.grad.numpy(), 2 * 2 * np.ones(3))  # doubled

    # removed handle: hook no longer fires
    handle.remove()
    x.clear_grad()
    y2 = paddle.sum(paddle.multiply(x, x))
    y2.backward()
    assert len(seen) == 1
    np.testing.assert_allclose(x.grad.numpy(), 2 * np.ones(3))


def test_register_hook_non_leaf_still_works():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    h = paddle.scale(x, 3.0)
    h.register_hook(lambda g: paddle.scale(g, 10.0))
    paddle.sum(h).backward()
    np.testing.assert_allclose(x.grad.numpy(), 30 * np.ones(3))


def test_int64_overflow_rejected():
    paddle.to_tensor(np.array([2**31 - 1], np.int64))  # max ok
    with pytest.raises(OverflowError, match="int32 range"):
        paddle.to_tensor(np.array([2**31], np.int64))
    with pytest.raises(OverflowError, match="int32 range"):
        paddle.to_tensor(np.array([-2**31 - 1], np.int64))
