"""Process-wide memoized greedy_reference oracle for the generation
suites.

The sequential full-recompute reference is the most expensive thing
these suites do: O(n) eager prefills over growing prefixes, each a pile
of tiny jnp dispatches.  test_generation, test_fused_decode, and
test_chunked_prefill all compare against the SAME (model config,
prompt, n) pairs — per-module caches re-pay the oracle once per file.
TinyCausalLM weights are deterministic per (seed, shape), so the
constructor signature is a sound cross-module cache key and the oracle
is computed exactly once per distinct comparison in the whole tier-1
run.
"""

_REFS = {}


def greedy_oracle(model, prompt, n, stop_tokens=()):
    key = (type(model).__name__, model.seed, model.vocab_size,
           model.num_layers, model.num_heads, model.head_dim,
           model.max_positions, tuple(int(t) for t in prompt), int(n),
           tuple(int(s) for s in stop_tokens))
    if key not in _REFS:
        _REFS[key] = model.greedy_reference(prompt, n, stop_tokens)
    return _REFS[key]
