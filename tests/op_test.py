"""OpTest harness — the per-op golden contract.

Reference parity: python/paddle/fluid/tests/unittests/op_test.py (OpTest:270,
check_output_with_place:1078, check_grad:1409, get_numeric_gradient:110): a
test declares an op, numpy inputs/attrs, expected outputs; check_output runs
the op through the eager dispatcher AND the static Program/Executor (the op
is emitted into a program and executed through the compiled-block path), and
check_grad compares analytic gradients against central finite differences.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def get_numeric_gradient(fn, inputs, wrt_idx, out_reduce=None, delta=1e-3):
    """Central finite differences of sum(fn(*inputs)) w.r.t. inputs[wrt_idx]."""

    def scalar_out(*args):
        out = fn(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        total = 0.0
        for o in outs:
            total = total + float(np.sum(np.asarray(o.numpy(), np.float64)))
        return total

    x = inputs[wrt_idx].numpy().astype(np.float64)
    grad = np.zeros_like(x)
    flat = x.reshape(-1)
    gflat = grad.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + delta
        args = list(inputs)
        args[wrt_idx] = paddle.to_tensor(x.astype(np.float32))
        hi = scalar_out(*args)
        flat[i] = orig - delta
        args[wrt_idx] = paddle.to_tensor(x.astype(np.float32))
        lo = scalar_out(*args)
        flat[i] = orig
        gflat[i] = (hi - lo) / (2 * delta)
    return grad


class OpTest:
    """Subclass sets: self.op (callable over Tensors), self.inputs (dict
    name->np array), self.attrs (dict), self.expected (np array or callable
    producing it)."""

    op = None
    attrs = {}
    grad_rtol = 1e-2
    grad_atol = 1e-2
    out_rtol = 1e-5
    out_atol = 1e-6

    def make_inputs(self):
        raise NotImplementedError

    def ref(self, *arrays):
        raise NotImplementedError

    def run_op(self, *tensors):
        return type(self).op(*tensors, **self.attrs)

    def check_output(self):
        arrays = self.make_inputs()
        tensors = [paddle.to_tensor(a) for a in arrays]
        out = self.run_op(*tensors)
        outs = out if isinstance(out, (list, tuple)) else [out]
        refs = self.ref(*arrays)
        refs = refs if isinstance(refs, (list, tuple)) else [refs]
        for o, r in zip(outs, refs):
            np.testing.assert_allclose(
                np.asarray(o.numpy(), np.float64), np.asarray(r, np.float64),
                rtol=self.out_rtol, atol=self.out_atol,
            )
        self.check_output_static(arrays, refs)

    def check_output_static(self, arrays=None, refs=None):
        """Run the op through the static Program/Executor path: the op is
        emitted as a program op and executed via the compiled block
        (Program IR -> planner -> jit lowering -> feed/fetch), mirroring
        the reference's check_output_with_place static leg."""
        import paddle_tpu.static as static
        from paddle_tpu.static.nn_static import emit
        from paddle_tpu.core import autograd
        from paddle_tpu.core.tensor import _wrap_data

        if arrays is None:
            arrays = self.make_inputs()
        if refs is None:
            refs = self.ref(*arrays)
            refs = refs if isinstance(refs, (list, tuple)) else [refs]
        refs = [np.asarray(r) for r in refs]

        paddle.enable_static()
        try:
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                feed_vars = [
                    static.data(f"x{i}", list(a.shape), dtype=str(a.dtype))
                    for i, a in enumerate(arrays)
                ]

                def fn(*vals):
                    with autograd.no_grad():
                        out = self.run_op(*[_wrap_data(v) for v in vals])
                    if isinstance(out, (list, tuple)):
                        return tuple(o._data for o in out)
                    return out._data

                outs_spec = [(f"Out{i}", list(r.shape), str(r.dtype))
                             for i, r in enumerate(refs)]
                out_vars = emit(type(self).__name__,
                                [(f"X{i}", v) for i, v in
                                 enumerate(feed_vars)],
                                outs_spec, fn)
                if not isinstance(out_vars, list):
                    out_vars = [out_vars]
            exe = static.Executor()
            exe.run(startup)
            res = exe.run(main,
                          feed={f"x{i}": a for i, a in enumerate(arrays)},
                          fetch_list=out_vars)
            for got, r in zip(res, refs):
                np.testing.assert_allclose(
                    np.asarray(got, np.float64),
                    np.asarray(r, np.float64),
                    rtol=self.out_rtol, atol=self.out_atol,
                    err_msg=f"{type(self).__name__}: static path mismatch",
                )
        finally:
            paddle.disable_static()

    def check_grad(self, wrt=(0,), delta=1e-3):
        arrays = self.make_inputs()
        for idx in wrt:
            tensors = [
                paddle.to_tensor(a, stop_gradient=(i != idx))
                for i, a in enumerate(arrays)
            ]
            out = self.run_op(*tensors)
            outs = out if isinstance(out, (list, tuple)) else [out]
            total = None
            for o in outs:
                s = paddle.sum(o)
                total = s if total is None else paddle.add(total, s)
            total.backward()
            analytic = tensors[idx].grad.numpy().astype(np.float64)

            numeric = get_numeric_gradient(
                lambda *ts: self.run_op(*ts), [
                    paddle.to_tensor(a) for a in arrays
                ], idx, delta=delta,
            )
            np.testing.assert_allclose(
                analytic, numeric, rtol=self.grad_rtol, atol=self.grad_atol,
            )
