"""Chunked prefill with per-step prefill/decode interleaving.

Acceptance oracles (all CPU, conftest forces the backend):

1. TOKEN IDENTITY: chunked prefill (eager AND forced-jit) reproduces
   full-prefill generation token for token — greedy and
   seeded-stochastic batches, chunk sizes that don't divide the prompt
   length, and forced-preemption re-prefill (a victim re-prefills
   THROUGH CHUNKS).  The chunk-attention masking contributes exactly
   zero for masked keys (pinned below); end-to-end values differ from
   full prefill only by XLA's per-shape reduction association, the same
   standard the fused decode step is held to.
2. COMPILE MENU COLLAPSE: under chunking, prefill_compiles_total is
   O(1) in prompt length (one executable per pages bucket, chunk shape
   fixed) — new prompt lengths add ZERO compiles, while the full-prefill
   path compiles one executable per length bucket.
3. NO DECODE STALLS: every step runs one chunk AND the whole decode
   batch (the old token-budget/decode-owed dance died with the ragged
   step — tests/test_ragged_step.py — which runs both in ONE dispatch),
   pinned for a pathological 8k-token prompt against a full decode
   batch.
4. DECODE PRE-WARM: the fused decode executable a mid-prefill sequence
   will land in is compiled before its first decode step (counted with
   the `prewarm` tag), so the prefill->decode seam never retraces.

Plus the gen_bench interleave satellite: decode tokens/s during a
concurrent long-prompt prefill is strictly better chunked than full.
"""
import importlib.util
import math
import os

import numpy as np
import pytest

from paddle_tpu import generation as gen
from paddle_tpu.generation import metrics as gmetrics
from paddle_tpu.generation.decode_attention import (
    chunk_prefill_attention, chunk_prefill_attention_reference,
    dense_causal_reference)
from paddle_tpu.profiler.monitor import StatRegistry


@pytest.fixture(autouse=True)
def _fresh_generation_stats():
    reg = StatRegistry.instance()
    for name in list(reg.stats()):
        if name.startswith(gmetrics.PREFIX):
            reg.get_stat(name).reset()
    yield


@pytest.fixture(scope="module")
def model():
    return gen.TinyCausalLM(vocab_size=48, num_layers=2, num_heads=2,
                            head_dim=8, seed=3)


from gen_oracle import greedy_oracle as _ref  # noqa: E402  cross-module memo


def _engine(model, *, slots=4, pages=64, page_size=4, chunk=3, **kw):
    cfg = gen.GenerationConfig(max_decode_slots=slots, num_pages=pages,
                               page_size=page_size,
                               prefill_chunk_tokens=chunk, **kw)
    return gen.GenerationEngine(model, cfg, start=False)


PROMPTS = [[1, 2, 3], [7, 5], [9, 9, 9, 4, 2], [11]]


# ----------------------- chunk attention math ---------------------------


def test_chunk_attention_masked_keys_contribute_exactly_zero():
    """The exactness anchor: keys past a query's position (causal tail
    AND gather padding) contribute EXACTLY zero — swapping the masked
    tail values for garbage changes nothing, bit for bit.  Both calls
    use the SAME shapes (the contract is per-shape: changing the query
    count changes XLA's reduction strategy at the ulp level, which is
    exactly why the end-to-end oracle is token identity, not bitwise)."""
    rng = np.random.default_rng(0)
    T, H, D = 13, 2, 8
    k = rng.standard_normal((T, H, D)).astype(np.float32)
    v = rng.standard_normal((T, H, D)).astype(np.float32)
    q = rng.standard_normal((4, H, D)).astype(np.float32)
    start = 5
    out = np.asarray(chunk_prefill_attention_reference(
        q, k[:9], v[:9], start))
    k2, v2 = k.copy(), v.copy()
    k2[6:], v2[6:] = 1e6, -1e6  # garbage where row 0 (pos 5) can't look
    out2 = np.asarray(chunk_prefill_attention_reference(
        q, k2[:9], v2[:9], start))
    # row 0 (pos 5) sees only keys 0..5: bit-identical despite garbage
    np.testing.assert_array_equal(out[:1], out2[:1])
    # rows 1..3 CAN see the garbage keys: they must have moved, or the
    # mask is over-wide and the garbage never entered anything
    assert not np.array_equal(out[1:], out2[1:])


@pytest.mark.parametrize("start,n", [(0, 5), (5, 4), (6, 7), (4, 1),
                                     (0, 13), (12, 1)])
def test_chunk_attention_rows_match_dense_causal(start, n):
    """Chunk rows equal the corresponding dense-causal full-recompute
    rows to reduction-reassociation precision (ulp-level: XLA picks the
    reduction strategy per shape; the VALUES entering each row's
    reductions are identical by the masking construction)."""
    rng = np.random.default_rng(start * 17 + n)
    T, H, D = 13, 2, 8
    q = rng.standard_normal((T, H, D)).astype(np.float32)
    k = rng.standard_normal((T, H, D)).astype(np.float32)
    v = rng.standard_normal((T, H, D)).astype(np.float32)
    full = np.asarray(dense_causal_reference(q, k, v))
    out = np.asarray(chunk_prefill_attention_reference(
        q[start:start + n], k[:start + n], v[:start + n], start))
    np.testing.assert_allclose(out, full[start:start + n],
                               atol=1e-6, rtol=1e-6)


def test_chunk_attention_paged_gather_matches_concat_reference():
    """The paged entry point (pool + page table, the jitted chunk path's
    read) agrees with the concat reference; the padded gather tail is
    masked to exact zeros."""
    rng = np.random.default_rng(1)
    H, D, ps = 2, 8, 4
    pool = gen.DeviceKVPool(1, H, D, num_pages=16, page_size=ps)
    kv = rng.standard_normal((1, 21, H, D)).astype(np.float32)
    pool.allocate(0)
    pool.append_prefill(0, kv, -kv)
    pt, _ = pool.gather_block_tables([0])
    start, n = 13, 8
    q = rng.standard_normal((n, H, D)).astype(np.float32)
    paged = np.asarray(chunk_prefill_attention(
        q, *pool.layer_pools(0), pt[0], start, use_kernel=False))
    ref = np.asarray(chunk_prefill_attention_reference(
        q, kv[0], -kv[0], start))
    np.testing.assert_allclose(paged, ref, atol=1e-6, rtol=1e-6)


@pytest.mark.parametrize("layout", ["token", "kernel"])
def test_chunk_attention_pallas_interpret_matches_reference(layout):
    """The Pallas chunk kernel (interpret mode on CPU) implements the
    same semantics over either pool layout; online softmax reassociates,
    so small float tolerance."""
    rng = np.random.default_rng(2)
    H, D, ps = 2, 128, 8
    pool = gen.DeviceKVPool(1, H, D, num_pages=16, page_size=ps,
                            pool_layout=layout)
    kv = rng.standard_normal((1, 21, H, D)).astype(np.float32)
    pool.allocate(0)
    pool.append_prefill(0, kv, -kv)
    pt, _ = pool.gather_block_tables([0])
    start, n = 13, 8
    q = rng.standard_normal((n, H, D)).astype(np.float32)
    kp, vp = pool.layer_pools(0)
    ref = np.asarray(chunk_prefill_attention(
        q, kp, vp, pt[0], start, use_kernel=False, layout=layout))
    ker = np.asarray(chunk_prefill_attention(
        q, kp, vp, pt[0], start, use_kernel=True, interpret=True,
        layout=layout))
    np.testing.assert_allclose(ker, ref, atol=2e-5, rtol=2e-5)


def test_chunk_attention_pallas_first_chunk_empty_prefix():
    """start == 0 (nothing cached yet): purely causal over the chunk's
    own keys, no zero-length softmax garbage."""
    rng = np.random.default_rng(3)
    H, D, ps = 1, 128, 8
    pool = gen.DeviceKVPool(1, H, D, num_pages=4, page_size=ps)
    kv = rng.standard_normal((1, 8, H, D)).astype(np.float32)
    pool.allocate(0)
    pool.append_prefill(0, kv, -kv)
    pt, _ = pool.gather_block_tables([0])
    q = rng.standard_normal((8, H, D)).astype(np.float32)
    kp, vp = pool.layer_pools(0)
    ref = np.asarray(chunk_prefill_attention(q, kp, vp, pt[0], 0,
                                             use_kernel=False))
    ker = np.asarray(chunk_prefill_attention(q, kp, vp, pt[0], 0,
                                             use_kernel=True,
                                             interpret=True))
    np.testing.assert_allclose(ker, ref, atol=2e-5, rtol=2e-5)


# ------------------------- cache chunk surface ---------------------------


@pytest.mark.parametrize("cls", [gen.PagedKVCache, gen.DeviceKVPool])
def test_cache_write_prefill_tokens_and_gather_prefix_roundtrip(cls):
    """Per-layer chunk span writes + exact prefix gathers on both
    backends, spans crossing page boundaries; incremental reservation
    growth (reserve per chunk, not per prompt)."""
    c = cls(2, 2, 8, num_pages=8, page_size=4)
    c.allocate("s")
    rng = np.random.default_rng(4)
    full_k = rng.standard_normal((2, 11, 2, 8)).astype(np.float32)
    written = 0
    for n in (3, 5, 3):  # 11 tokens in chunks, crossing pages
        start = c.reserve("s", n)
        assert start == written
        for layer in range(2):
            c.write_prefill_tokens("s", start, layer,
                                   full_k[layer, start:start + n],
                                   -full_k[layer, start:start + n])
        written += n
        for layer in range(2):
            k, v = c.gather_prefix("s", layer, written)
            np.testing.assert_array_equal(np.asarray(k),
                                          full_k[layer, :written])
            np.testing.assert_array_equal(np.asarray(v),
                                          -full_k[layer, :written])
    assert c.seq_len("s") == 11


def test_cache_gather_prefix_typed_errors():
    c = gen.PagedKVCache(1, 1, 4, num_pages=4, page_size=2)
    with pytest.raises(gen.UnknownSequenceError):
        c.gather_prefix("nope", 0, 1)
    c.allocate("s")
    c.reserve("s", 3)
    with pytest.raises(IndexError):
        c.gather_prefix("s", 0, 4)  # beyond the reservation
    with pytest.raises(IndexError):
        c.write_prefill_tokens("s", 2, 0, np.zeros((2, 1, 4)),
                               np.zeros((2, 1, 4)))


# ---------------------- token identity oracles ---------------------------


@pytest.mark.parametrize("chunk", [1, 2, 3])
def test_chunked_greedy_token_identical_to_oracle(model, chunk):
    """Oracle 1: chunk sizes that don't divide the prompt lengths, all
    prompts, greedy — token identical to sequential full recompute."""
    eng = _engine(model, chunk=chunk)
    handles = [eng.submit(p, max_new_tokens=12) for p in PROMPTS]
    eng.run_until_idle()
    for h, p in zip(handles, PROMPTS):
        assert h.result(timeout=5).token_ids == _ref(model, p, 12)
    stats = eng.metrics.snapshot()
    expected_chunks = sum(math.ceil(len(p) / chunk) for p in PROMPTS)
    assert stats["generation.prefill_chunks_total"] == expected_chunks
    assert eng.cache.utilization() == 0.0
    eng.shutdown()


def test_chunked_stochastic_token_identical_to_full(model):
    """Oracle 1 (stochastic): seeded temperature/top-k/top-p streams are
    identical chunked vs full prefill."""
    def run(chunk):
        eng = _engine(model, chunk=chunk)
        hs = [eng.submit(p, max_new_tokens=10,
                         sampling=gen.SamplingParams(
                             temperature=0.9, top_k=10, top_p=0.9,
                             seed=41 + i))
              for i, p in enumerate(PROMPTS)]
        eng.run_until_idle()
        out = [h.result(timeout=5).token_ids for h in hs]
        eng.shutdown()
        return out

    assert run(3) == run(0) == run(2)


def test_chunked_token_identical_under_forced_preemption(model):
    """Oracle 1 (preemption): a pool sized to thrash — victims (decoding
    AND mid-prefill) re-prefill THROUGH CHUNKS and every token still
    matches; mid-prefill victims restart from position 0."""
    eng = _engine(model, pages=9, chunk=2)
    handles = [eng.submit(p, max_new_tokens=12) for p in PROMPTS]
    eng.run_until_idle()
    results = [h.result(timeout=5) for h in handles]
    for res, p in zip(results, PROMPTS):
        assert res.token_ids == _ref(model, p, 12)
    assert sum(r.preemptions for r in results) > 0
    # re-prefills ran through the chunk path: more chunks than one clean
    # pass over every prompt would need
    clean = sum(math.ceil(len(p) / 2) for p in PROMPTS)
    stats = eng.metrics.snapshot()
    assert stats["generation.prefill_chunks_total"] > clean
    assert eng.cache.utilization() == 0.0
    eng.shutdown()


def test_chunked_device_backend_token_identical(model):
    eng = _engine(model, chunk=3, kv_backend="device")
    handles = [eng.submit(p, max_new_tokens=10) for p in PROMPTS]
    eng.run_until_idle()
    for h, p in zip(handles, PROMPTS):
        assert h.result(timeout=5).token_ids == _ref(model, p, 10)
    eng.shutdown()


def test_chunked_jit_path_token_identical(model):
    """The jitted donated-pool chunk dispatch (ChunkedPrefillStep,
    forced on CPU like the fused decode tests): token identity incl.
    preemption re-prefill."""
    eng = _engine(model, chunk=3, pages=9, kv_backend="device",
                  jit_prefill=True)
    assert eng._chunk_step is not None
    handles = [eng.submit(p, max_new_tokens=12) for p in PROMPTS]
    eng.run_until_idle()
    results = [h.result(timeout=5) for h in handles]
    for res, p in zip(results, PROMPTS):
        assert res.token_ids == _ref(model, p, 12)
    assert sum(r.preemptions for r in results) > 0
    eng.shutdown()


def test_chunked_jit_fused_decode_token_identical(model):
    """Chunked jit prefill + fused single-dispatch decode together —
    the full TPU-shaped pipeline, CPU-forced."""
    eng = _engine(model, chunk=3, kv_backend="device", jit_prefill=True,
                  decode="fused")
    handles = [eng.submit(p, max_new_tokens=10) for p in PROMPTS]
    eng.run_until_idle()
    for h, p in zip(handles, PROMPTS):
        assert h.result(timeout=5).token_ids == _ref(model, p, 10)
    eng.shutdown()


def test_chunked_max_new_tokens_zero_and_stop_tokens(model):
    eng = _engine(model, chunk=2)
    free = _ref(model, [1, 2, 3], 8)
    h0 = eng.submit([1, 2], max_new_tokens=0)
    hs = eng.submit([1, 2, 3], max_new_tokens=8, stop_tokens=(free[2],))
    eng.run_until_idle()
    assert h0.result(timeout=5).token_ids == []
    assert h0.result().finish_reason == "length"
    res = hs.result(timeout=5)
    assert res.finish_reason == "stop" and res.token_ids == free[:2]
    assert eng.cache.utilization() == 0.0
    eng.shutdown()


def test_chunked_background_worker_end_to_end(model):
    eng = _engine(model, chunk=2)
    eng.start()
    try:
        h = eng.submit([5, 6, 7], max_new_tokens=8)
        assert list(h.tokens(timeout=30)) == _ref(model, [5, 6, 7], 8)
    finally:
        eng.shutdown()


# -------------------- compile-menu collapse (oracle 2) -------------------


def test_chunked_prefill_compiles_constant_in_prompt_length(model):
    """Oracle 2: new prompt lengths add ZERO chunk compiles (the chunk
    shape is fixed; only pages buckets compile), while the full-prefill
    path compiles one executable per length bucket it meets."""
    lengths_a = [18, 21, 24]
    lengths_b = [19, 22, 26, 28, 30]  # new lengths, same pages ballpark
    menu = tuple(range(17, 33))       # one length bucket per length

    def run(chunked, lengths):
        # the compiles stat is process-global (StatRegistry singleton):
        # four engines run inside this one test, so count the DELTA
        stat = StatRegistry.instance().get_stat(
            gmetrics.PREFILL_COMPILES_TOTAL)
        before = stat.get()
        eng = _engine(model, chunk=4 if chunked else 0, pages=64,
                      page_size=16, kv_backend="device",
                      jit_prefill=True,
                      prefill_length_buckets=menu)
        rng = np.random.default_rng(7)
        for n in lengths:
            h = eng.submit(rng.integers(1, 40, n).tolist(),
                           max_new_tokens=1)
            eng.run_until_idle()
            h.result(timeout=5)
        compiles = stat.get() - before
        eng.shutdown()
        return compiles

    chunked_a = run(True, lengths_a)
    chunked_ab = run(True, lengths_a + lengths_b)
    full_a = run(False, lengths_a)
    full_ab = run(False, lengths_a + lengths_b)
    # chunked: O(1) in prompt length — extra lengths, zero new compiles
    assert chunked_ab == chunked_a
    # full prefill: every new length bucket pays a compile
    assert full_ab == full_a + len(lengths_b)
    assert chunked_ab < full_ab


def test_chunked_repeat_traffic_no_recompiles(model):
    eng = _engine(model, chunk=3, kv_backend="device", jit_prefill=True)

    def burst():
        hs = [eng.submit(p, max_new_tokens=4) for p in PROMPTS]
        eng.run_until_idle()
        for h in hs:
            h.result(timeout=5)

    burst()
    first = eng._chunk_step.compile_count
    assert first >= 1
    burst()
    assert eng._chunk_step.compile_count == first
    stats = eng.metrics.snapshot()
    assert stats["generation.prefill_compiles_total"] == first
    assert stats["generation.prefill_cache_hits"] > 0
    eng.shutdown()


# ---------------------- per-step prefill plan ----------------------------


def test_plan_step_serves_chunk_and_decode_together(model):
    """Scheduler unit: the plan is simply the oldest mid-prefill
    sequence's next chunk — the decode batch always runs alongside.
    The decode-owed stall dance is GONE (the ragged step runs chunk
    and decode in one dispatch; the legacy path runs both of its
    dispatches every step)."""
    eng = _engine(model, chunk=4, slots=4)
    sched = eng.scheduler
    hs = [eng.submit(p, max_new_tokens=8) for p in PROMPTS[:3]]
    for _ in range(6):
        eng.step()
    assert len(sched.decode_ready()) == 3
    eng.submit([1] * 20, max_new_tokens=1)
    sched.admit(limit=4)
    chunk_state, chunk_len = sched.plan_step(4)
    assert chunk_state is not None and chunk_len == 4
    # the plan is stateless: asking again plans the same chunk
    again, n_again = sched.plan_step(4)
    assert again is chunk_state and n_again == 4
    # max_chunk clips to the packed-axis room the ragged caller has
    clipped, n_clip = sched.plan_step(4, max_chunk=3)
    assert clipped is chunk_state and n_clip == 3
    assert sched.plan_step(4, max_chunk=0) == (None, 0)
    eng.run_until_idle()
    for h, p in zip(hs, PROMPTS[:3]):
        assert h.result(timeout=5).token_ids == _ref(model, p, 8)
    eng.shutdown()


def test_chunked_oldest_prefill_served_first(model):
    eng = _engine(model, chunk=2, slots=4)
    eng.submit([1] * 6, max_new_tokens=1)
    eng.submit([2] * 6, max_new_tokens=1)
    eng.scheduler.admit(limit=4)
    first = eng.scheduler.prefilling()
    assert [s.seq_id for s in first] == sorted(s.seq_id for s in first)
    state, n = eng.scheduler.plan_step(2)
    assert state is first[0] and n == 2
    eng.run_until_idle()
    eng.shutdown()


def test_decode_never_stalls_for_8k_prompt_against_full_batch():
    """The pathological case the old token budget existed for: an
    8192-token prompt streams in against a FULL decode batch.  With the
    budget dance deleted, every step now runs one chunk AND the whole
    decode batch — the decode streams advance every single step of the
    long prefill window, stay token-identical, and the long prompt's
    first token is the full-prefill argmax."""
    model = gen.TinyCausalLM(vocab_size=32, num_layers=1, num_heads=1,
                             head_dim=8, max_positions=8300, seed=5)
    chunk = 1024
    eng = gen.GenerationEngine(model, gen.GenerationConfig(
        max_decode_slots=4, num_pages=135, page_size=64,
        prefill_chunk_tokens=chunk),
        start=False)
    shorts = [[1, 2, 3], [7, 5], [9, 4]]
    hs = [eng.submit(p, max_new_tokens=24) for p in shorts]
    for _ in range(4):
        eng.step()
    assert len(eng.scheduler.decode_ready()) == 3  # the full decode batch
    rng = np.random.default_rng(6)
    long_prompt = rng.integers(0, 32, 8192).tolist()
    h_long = eng.submit(long_prompt, max_new_tokens=1)
    tok_stat = StatRegistry.instance().get_stat(gmetrics.TOKENS_TOTAL)
    stall_free = True
    for _ in range(64):
        before = tok_stat.get()
        eng.step()
        if eng.scheduler.decode_ready() and tok_stat.get() == before:
            stall_free = False   # a step with live decode slots that
            # emitted no token — the starvation the old budget caused
        if not eng.scheduler.prefilling():
            break
    assert stall_free
    eng.run_until_idle()
    for h, p in zip(hs, shorts):
        assert h.result(timeout=5).token_ids == \
            model.greedy_reference(p, 24)
    import jax.numpy as jnp

    logits, _, _ = model.prefill(jnp.asarray(long_prompt, jnp.int32))
    assert h_long.result(timeout=5).token_ids == \
        [int(np.argmax(np.asarray(logits)))]
    assert eng.cache.utilization() == 0.0
    eng.shutdown()


# ------------------------- decode pre-warm -------------------------------


def test_decode_bucket_prewarmed_during_prefill(model):
    """Oracle 4: with pages pinned to one bucket, the (batch, pages,
    greedy) executable the joining sequence lands in is compiled DURING
    its prefill (the prewarm tag), and the join itself adds zero
    compiles."""
    cfg = gen.GenerationConfig(max_decode_slots=4, num_pages=8,
                               page_size=64, prefill_chunk_tokens=4,
                               kv_backend="device", decode="fused",
                               jit_prefill=True)
    eng = gen.GenerationEngine(model, cfg, start=False)
    h1 = eng.submit([1, 2, 3], max_new_tokens=24)
    for _ in range(4):
        eng.step()
    long_p = [int(t) for t in
              np.random.default_rng(5).integers(1, 40, 14)]
    h2 = eng.submit(long_p, max_new_tokens=4)
    eng.step()  # first chunk of h2: prewarm of (batch 2, pages 1) fires
    stats = eng.metrics.snapshot()
    assert stats["generation.decode_compiles_prewarm"] >= 1
    compiles_mid = stats["generation.decode_compiles_total"]
    eng.run_until_idle()
    stats = eng.metrics.snapshot()
    assert stats["generation.decode_compiles_total"] == compiles_mid, \
        "the first decode after prefill retraced its bucket"
    assert h1.result(timeout=5).token_ids == _ref(model, [1, 2, 3], 24)
    assert h2.result(timeout=5).token_ids == \
        model.greedy_reference(long_p, 4)
    eng.shutdown()


def test_prewarm_decode_public_api_counts_tag(model):
    eng = _engine(model, chunk=0, kv_backend="device", decode="fused")
    assert eng.prewarm_decode(2, 1, greedy=True) is True
    assert eng.prewarm_decode(2, 1, greedy=True) is False  # cached
    stats = eng.metrics.snapshot()
    assert stats["generation.decode_compiles_prewarm"] == 1
    assert stats["generation.decode_compiles_total"] == 1
    eng.shutdown()

    eager = _engine(model, chunk=0)
    assert eager.prewarm_decode(2, 1) is False  # no-op without fused
    eager.shutdown()


# --------------------------- config policy -------------------------------


def test_chunked_config_validation(model):
    with pytest.raises(ValueError):
        gen.GenerationConfig(prefill_chunk_tokens=-1)
    with pytest.raises(ValueError):
        gen.GenerationConfig(step_token_budget=0)

    class NoChunk:
        num_layers, num_heads, head_dim, vocab_size = 1, 1, 4, 8

        def prefill(self, tokens):
            raise NotImplementedError

        def decode(self, tokens, positions, attend):
            raise NotImplementedError

    with pytest.raises(ValueError, match="prefill_chunk"):
        gen.GenerationEngine(NoChunk(), gen.GenerationConfig(
            prefill_chunk_tokens=4), start=False)
    # auto on CPU: chunking off, full prefill stays the tier-1 default
    eng = gen.GenerationEngine(model, gen.GenerationConfig(), start=False)
    assert eng.prefill_chunk_tokens == 0
    eng.shutdown()


class _HidingModel:
    """Delegating wrapper that hides a set of protocol attributes."""

    def __init__(self, inner, hide):
        self._inner = inner
        self._hide = frozenset(hide)

    def __getattr__(self, name):
        if name in self._hide:
            raise AttributeError(name)
        return getattr(self._inner, name)


def test_auto_chunk_policy_requires_servable_jit_path(model, monkeypatch):
    """Auto (prefill_chunk_tokens=None) picks chunking ONLY when a
    jitted chunk path can actually serve it: for a model WITHOUT the
    ragged protocol, jit_prefill=False must degrade to full prefill
    (never raise on a config the user didn't write), and an eager-only
    chunk protocol never auto-enables on TPU (the per-layer eager loop
    would regress TTFT vs one jitted prefill — eager chunking is
    explicit opt-in).  A ragged-capable model auto-chunks through the
    ragged dispatch instead — jit_prefill is irrelevant there."""
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    eng = gen.GenerationEngine(
        _HidingModel(model, ("prefill_chunk", "ragged_step_fn")),
        gen.GenerationConfig(jit_prefill=False, use_kernel=False),
        start=False)
    assert eng.prefill_chunk_tokens == 0 and eng._chunk_step is None
    eng.shutdown()
    # host pools make every jit path unavailable; the eager protocol
    # (TinyCausalLM.prefill_chunk) alone must not auto-enable
    eng = gen.GenerationEngine(
        model, gen.GenerationConfig(kv_backend="host", use_kernel=False),
        start=False)
    assert eng.prefill_chunk_tokens == 0
    eng.shutdown()
    # with the legacy jit path available (ragged hidden), auto DOES
    # chunk on TPU through ChunkedPrefillStep
    eng = gen.GenerationEngine(
        _HidingModel(model, ("ragged_step_fn",)),
        gen.GenerationConfig(kv_backend="device", use_kernel=False),
        start=False)
    assert eng.prefill_chunk_tokens == gen.DEFAULT_PREFILL_CHUNK_TOKENS
    assert eng._chunk_step is not None
    eng.shutdown()
    # a ragged-capable model auto-selects the RAGGED step on TPU:
    # chunks ride the one mixed-batch dispatch, even with
    # jit_prefill=False (the ragged executable needs no prefill cache)
    eng = gen.GenerationEngine(
        model, gen.GenerationConfig(kv_backend="device",
                                    jit_prefill=False, use_kernel=False),
        start=False)
    assert eng.step_mode == "ragged" and eng._ragged is not None
    assert eng.prefill_chunk_tokens == gen.DEFAULT_PREFILL_CHUNK_TOKENS
    assert eng._chunk_step is None and eng._fused is None
    eng.shutdown()


# ------------------- gen_bench interleave satellite ----------------------


def _load_gen_bench():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "gen_bench.py")
    spec = importlib.util.spec_from_file_location("gen_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_gen_bench_interleave_chunked_beats_full_decode_throughput():
    """The acceptance A/B: decode tokens/s (and raw token count) during
    a concurrent long-prompt prefill is strictly better chunked than
    full — full prefill head-of-line-blocks the decode batch for the
    whole prompt, chunking interleaves."""
    gb = _load_gen_bench()
    model = gen.TinyCausalLM(vocab_size=64, num_layers=2, num_heads=2,
                             head_dim=8, max_positions=256, seed=0)
    cells = {
        mode: gb.bench_interleave(model, batch=4, context=8,
                                  long_context=96, new_tokens=16,
                                  page_size=8, pool="host",
                                  decode="eager", prefill=mode,
                                  chunk_tokens=8)
        for mode in ("full", "chunked")
    }
    full, chunked = cells["full"], cells["chunked"]
    # the long prompt's 12 chunks (96 / 8) plus the late packing
    # probe's (a short admitted BEHIND the long prompt rides the same
    # window through plan_pack — its chunk count depends on the
    # leftover room per step, so pin a range, not an exact count)
    assert chunked["prefill_chunks"] >= 12
    assert full["prefill_chunks"] == 0
    assert chunked["decode_tokens_during_prefill"] > \
        full["decode_tokens_during_prefill"]
    assert chunked["decode_tps_during_prefill"] > \
        full["decode_tps_during_prefill"]
    # the multi-prompt packing probe: the short admitted behind the
    # long prompt gets its first token WITHOUT waiting out the long
    # prefill — with packing its TTFT sits well under the long
    # prompt's own (the unpacked short would have paid the whole
    # remaining window first); the direct packed-vs-unpacked A/B is
    # test_gen_bench_packing_ab below
    assert 0 < chunked["ttft_short_behind_long_s"] < \
        chunked["ttft_long_s"]
    assert full["ttft_short_behind_long_s"] > 0
    # steady state: the measured pass compiles nothing in either mode
    assert full["measured_prefill_compiles"] == 0
    assert chunked["measured_prefill_compiles"] == 0


def test_gen_bench_packing_ab():
    """The packing acceptance A/B on CPU: the SAME chunked interleave
    traffic with multi-prompt packing on vs off (prefill_pack=False =
    one chunk per step) — packing strictly improves the TTFT of the
    short prompt admitted behind the long one."""
    gb = _load_gen_bench()
    model = gen.TinyCausalLM(vocab_size=64, num_layers=2, num_heads=2,
                             head_dim=8, max_positions=256, seed=0)
    cells = {
        pack: gb.bench_interleave(model, batch=4, context=8,
                                  long_context=96, new_tokens=16,
                                  page_size=8, pool="host",
                                  decode="eager", prefill="chunked",
                                  chunk_tokens=8, pack=pack)
        for pack in (True, False)
    }
    packed, unpacked = cells[True], cells[False]
    assert packed["pack"] is True and unpacked["pack"] is False
    # unpacked: the late short waits out every remaining long chunk
    # before its own prefill starts; packed: it rides the next step's
    # leftover room
    assert packed["ttft_short_behind_long_s"] < \
        unpacked["ttft_short_behind_long_s"]


def test_gen_bench_cell_reports_measured_compiles(model):
    """Satellite: pre-warm moves bucket compiles out of the measured
    window — the steady-state cell reports measured_compiles == 0 on
    the fused decode path."""
    gb = _load_gen_bench()
    cell = gb.bench_cell(model, batch=4, context=8, new_tokens=8,
                         num_pages=32, page_size=8, pool="device",
                         decode="fused")
    assert cell["measured_compiles"] == 0
    assert cell["dispatches_per_step"] == 1
    assert cell["warmup_s"] > 0


def test_legacy_interleaved_step_reports_two_dispatches(model):
    """The legacy chunked step really issues TWO device programs when a
    chunk and the decode batch share a step (jitted chunk + fused
    decode): the per-step dispatch gauge must say 2 — the number the
    ragged step's 1 is measured against (gen_bench --step A/B)."""
    eng = _engine(model, chunk=2, kv_backend="device", jit_prefill=True,
                  decode="fused")
    h1 = eng.submit([1, 2, 3], max_new_tokens=16)
    for _ in range(4):                 # h1 through prefill into decode
        eng.step()
    assert eng.scheduler.decode_ready()
    h2 = eng.submit([1] * 8, max_new_tokens=1)
    eng.scheduler.admit(limit=4)
    assert eng.scheduler.prefilling()
    eng.step()                         # chunk dispatch + decode dispatch
    stats = eng.metrics.snapshot()
    assert stats["generation.decode_dispatches_per_step"] == 2
    eng.run_until_idle()
    h1.result(timeout=5)
    h2.result(timeout=5)
    eng.shutdown()
