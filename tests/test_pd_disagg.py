"""Prefill/decode disaggregation as a ROUTING POLICY, plus the cancel
transport op (ISSUE 17 rung 2).

The contract under test:

- A prefill-class replica parks every sequence the moment its prompt
  is consumed (the exact live-migration export: page bytes, RNG,
  counters); the router collects the parked snapshot and places it on
  a decode-class sibling via import_sequence with base=n_generated —
  so the split is ZERO-REPLAY by construction, and the client stream
  is one exact prefix regardless of which side emitted what.
- Role is a routing PREFERENCE, never a wall: prompts at or past
  `pd_prefill_threshold_tokens` prefer the prefill class, shorter
  ones the decode class, mixed replicas belong to both — and a fleet
  of all-mixed replicas (the ablation baseline) never hands off.
- cancel(handle) frees the queue slot and pages wherever the request
  lives and resolves the client with finish_reason="cancelled" —
  an abandoning client never hangs and never keeps paying.
"""
import time

import pytest

from paddle_tpu import generation as gen
from paddle_tpu.generation.sampling import SamplingParams
from paddle_tpu.profiler.monitor import StatRegistry
from paddle_tpu.serving import fleet as fleet_mod
from paddle_tpu.serving.fleet import (FleetConfig, FleetRouter,
                                      ReplicaSpec)

from dist_capability import (SUBPROC_SKIP_REASON,  # noqa: E402
                             subprocess_replicas_available)
from gen_oracle import greedy_oracle as _ref  # noqa: E402

needs_subproc = pytest.mark.skipif(
    not subprocess_replicas_available(), reason=SUBPROC_SKIP_REASON)

SYSTEM = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]   # 12 tokens
LONG = [SYSTEM + [7, 7], SYSTEM + [1], SYSTEM + [9, 9, 9], SYSTEM + [2]]


@pytest.fixture(autouse=True)
def _fresh_fleet_stats():
    reg = StatRegistry.instance()
    for name in list(reg.stats()):
        if name.startswith(fleet_mod.PREFIX):
            reg.get_stat(name).reset()
    yield


@pytest.fixture(scope="module")
def model():
    return gen.TinyCausalLM(vocab_size=48, num_layers=2, num_heads=2,
                            head_dim=8, seed=3)


def _cfg(**kw):
    base = dict(max_decode_slots=4, num_pages=64, page_size=4,
                prefix_cache=True)
    base.update(kw)
    return gen.GenerationConfig(**kw and base or base)


def _stat(name):
    return StatRegistry.instance().get_stat(name).get()


def _split_fleet(model, threshold=8, n_decode=1, **fleet_kw):
    """One prefill replica + n decode replicas, threshold low enough
    that every LONG prompt classifies as prefill work."""
    specs = [ReplicaSpec("pf0", model, _cfg(), role="prefill")]
    specs += [ReplicaSpec(f"dc{i}", model, _cfg(), role="decode")
              for i in range(n_decode)]
    kw = dict(start=True, seed=0,
              pd_prefill_threshold_tokens=threshold)
    kw.update(fleet_kw)
    return FleetRouter(specs, FleetConfig(**kw))


def _requests_per_replica(fl):
    snap = fl.stats_snapshot()
    return {n: r.get("generation", {}).get(
                "generation.requests_total", 0)
            for n, r in snap["replicas"].items()}


# --------------------------- the split path ------------------------------


def test_split_fleet_token_identity_zero_replay(model):
    """The headline invariant: split P/D streams are token-identical
    to the single-engine oracle, every long prompt hands off exactly
    once, and the import-at-base design replays ZERO tokens."""
    fl = _split_fleet(model)
    try:
        hs = [fl.submit(p, max_new_tokens=8) for p in LONG]
        for p, h in zip(LONG, hs):
            r = h.result(timeout=60)
            assert r.token_ids == _ref(model, p, 8)
            assert r.finish_reason == "length"
        assert _stat(fleet_mod.PD_HANDOFFS) == len(LONG)
        assert _stat(fleet_mod.PD_HANDOFF_TOKENS) >= len(LONG)
        assert _stat(fleet_mod.PD_HANDOFF_WALL_S) >= 0.0
        assert _stat(fleet_mod.ROUTED_ROLE) == len(LONG)
        assert _stat(fleet_mod.MIGRATED_REPLAY_TOKENS) == 0
        assert _stat(fleet_mod.LIVE_MIGRATED_TOTAL) == len(LONG)
    finally:
        fl.shutdown()


def test_split_fleet_stochastic_stream_through_handoff(model):
    """Seeded sampling survives the handoff: the RNG state rides the
    snapshot, so the decode side continues the SAME stream the
    prefill side started — identical to one engine end to end."""
    sp = SamplingParams(temperature=0.8, top_k=6, seed=77)
    fl = _split_fleet(model)
    try:
        h = fl.submit(SYSTEM, max_new_tokens=10, sampling=sp)
        got = h.result(timeout=60).token_ids
    finally:
        fl.shutdown()
    eng = gen.GenerationEngine(model, _cfg(), start=False)
    ho = eng.submit(SYSTEM, max_new_tokens=10,
                    sampling=SamplingParams(temperature=0.8, top_k=6,
                                            seed=77))
    eng.run_until_idle()
    assert got == ho.result(timeout=10).token_ids
    assert _stat(fleet_mod.PD_HANDOFFS) == 1
    eng.shutdown()


def test_role_threshold_segregates_traffic(model):
    """Short interactive prompts route to the decode class and stay
    there; long prompts prefill on the prefill class and hand off.
    requests_total counts SUBMITTED work, so the split is visible
    per replica."""
    fl = _split_fleet(model, threshold=10)
    try:
        short = [fl.submit([5, 6], max_new_tokens=4)
                 for _ in range(3)]
        longs = [fl.submit(p, max_new_tokens=4) for p in LONG[:2]]
        for h, p in zip(short, [[5, 6]] * 3):
            assert h.result(timeout=60).token_ids == _ref(model, p, 4)
        for h, p in zip(longs, LONG[:2]):
            assert h.result(timeout=60).token_ids == _ref(model, p, 4)
        per = _requests_per_replica(fl)
        assert per["pf0"] == 2          # only the long prompts
        # the decode replica ran the 3 short prompts PLUS the 2
        # imported continuations (import_sequence counts a request)
        assert per["dc0"] == 5
        assert _stat(fleet_mod.PD_HANDOFFS) == 2
        # 5 client submits, both classes count; a handoff that falls
        # to the cold ladder (decode slots momentarily full) counts
        # its decode-pinned placement too
        assert _stat(fleet_mod.ROUTED_ROLE) >= 5
    finally:
        fl.shutdown()


def test_mixed_ablation_never_hands_off(model):
    """role="mixed" everywhere is the A/B baseline: same prompts,
    token-identical, zero handoffs, zero role routing — the P/D rung
    is provably inert without roles."""
    specs = [ReplicaSpec(f"m{i}", model, _cfg()) for i in range(2)]
    fl = FleetRouter(specs, FleetConfig(start=True, seed=0,
                                        pd_prefill_threshold_tokens=8))
    try:
        hs = [fl.submit(p, max_new_tokens=8) for p in LONG]
        for p, h in zip(LONG, hs):
            assert h.result(timeout=60).token_ids == _ref(model, p, 8)
        assert _stat(fleet_mod.PD_HANDOFFS) == 0
        assert _stat(fleet_mod.ROUTED_ROLE) == 0
    finally:
        fl.shutdown()


def test_stepped_fleet_collects_handoffs_without_threads(model):
    """The pull model needs no wakeups: a start=False fleet moves
    parked snapshots through run_until_idle's collection backstop —
    fully deterministic, single-threaded."""
    fl = _split_fleet(model, start=False)
    try:
        h = fl.submit(SYSTEM, max_new_tokens=6)
        fl.run_until_idle()
        assert h.result(timeout=10).token_ids == _ref(model, SYSTEM, 6)
        assert _stat(fleet_mod.PD_HANDOFFS) == 1
        assert _stat(fleet_mod.MIGRATED_REPLAY_TOKENS) == 0
    finally:
        fl.shutdown()


def test_watchdog_backstop_collects_when_poke_disabled(model):
    """Event wakeups are an optimization, not a correctness
    dependency: with the prefill engine's on_handoff notification
    severed, the router watchdog's periodic collection still moves
    the parked snapshot and the stream completes."""
    fl = _split_fleet(model, watchdog_interval_s=0.05)
    try:
        fl._replicas["pf0"].transport.engine.on_handoff = None
        h = fl.submit(SYSTEM, max_new_tokens=6)
        assert h.result(timeout=30).token_ids == _ref(model, SYSTEM, 6)
        assert _stat(fleet_mod.PD_HANDOFFS) == 1
    finally:
        fl.shutdown()


def test_prefill_death_after_handoff_loses_nothing(model):
    """A prefill replica dying right after its snapshots were parked
    parent-side: _handle_death drains the parked handoffs FIRST, so
    the streams complete on the decode class with zero replay."""
    fl = _split_fleet(model, start=False)
    try:
        h = fl.submit(SYSTEM, max_new_tokens=8)
        pf = fl._replicas["pf0"]
        # park the snapshot inside the prefill engine, then kill the
        # replica before ANY collection ran
        eng = pf.transport.engine
        eng.on_handoff = None
        for _ in range(50):
            if eng.handoffs_pending():
                break
            eng.step()
        assert eng.handoffs_pending()
        pf.state = "dead"
        for item in pf.transport.take_handoffs():
            fl._place_handoff(item, exclude="pf0")
        fl.run_until_idle()
        assert h.result(timeout=10).token_ids == _ref(model, SYSTEM, 8)
        assert _stat(fleet_mod.PD_HANDOFFS) == 1
        assert _stat(fleet_mod.MIGRATED_REPLAY_TOKENS) == 0
    finally:
        fl.shutdown()


@pytest.mark.slow
@needs_subproc
def test_prefill_sigkill_over_proc_streams_complete(model):
    """The acceptance drill: SIGKILL the prefill replica mid-wave over
    a real process boundary.  Parent-side parked snapshots and the
    in-flight ledger together guarantee every stream completes
    token-identical, and the decode pools leak nothing.  (Replay MAY
    be nonzero here: a kill can land before the handoff frame left.)"""
    fl = _split_fleet(model, transport="proc", n_decode=1,
                      respawn_backoff_s=0.05,
                      heartbeat_dead_after=2.0,
                      watchdog_interval_s=0.1)
    try:
        hs = [fl.submit(p, max_new_tokens=8) for p in LONG]
        time.sleep(0.2)
        fl._replicas["pf0"].transport.kill()
        for p, h in zip(LONG, hs):
            assert h.result(timeout=120).token_ids == _ref(model, p, 8)
        # every page accounted for on the survivor
        dc = fl._replicas["dc0"].transport
        dc.flush_prefix()
        deadline = time.monotonic() + 30
        while dc.stats()["cache"]["pages_in_use"]:
            assert time.monotonic() < deadline
            time.sleep(0.05)
            dc.flush_prefix()
    finally:
        fl.shutdown()


# ------------------------------ cancel -----------------------------------


def test_engine_cancel_active_stream_frees_everything(model):
    """Cancel a live decode slot: the stream resolves with
    finish_reason="cancelled" and an exact prefix, the slot frees,
    and after a flush the pool holds zero pages."""
    eng = gen.GenerationEngine(model, _cfg(), start=False)
    h = eng.submit(SYSTEM, max_new_tokens=200)
    for _ in range(6):
        eng.step()
    assert eng.cancel(h) is True
    r = h.result(timeout=10)
    assert r.finish_reason == "cancelled"
    # oracle only as deep as the cancelled stream got — the full
    # 200-token reference would dwarf the test
    oracle = _ref(model, SYSTEM, max(1, len(r.token_ids)))
    assert r.token_ids == oracle[:len(r.token_ids)]
    assert eng.cancel(h) is False          # idempotent: owns nothing
    eng.run_until_idle()
    eng.cache.flush_prefix_cache()
    assert eng.cache.stats()["pages_in_use"] == 0
    eng.shutdown()


def test_engine_cancel_queued_request_never_hangs(model):
    """Cancel a request still in the admission queue: zero tokens,
    typed finish, and the queue slot is actually given back (the
    follow-up request admits and completes)."""
    eng = gen.GenerationEngine(model, _cfg(), start=False)
    victim = eng.submit(SYSTEM, max_new_tokens=8)
    assert eng.cancel(victim) is True
    r = victim.result(timeout=10)
    assert r.finish_reason == "cancelled" and r.token_ids == []
    survivor = eng.submit(SYSTEM, max_new_tokens=8)
    eng.run_until_idle()
    assert survivor.result(timeout=10).token_ids == \
        _ref(model, SYSTEM, 8)
    eng.shutdown()


def test_inproc_transport_cancel_paths(model):
    """The transport op the fleet exposes: True exactly when the
    replica owns the stream, False after it resolved — and a split
    fleet's prefill-parked stream cancels cleanly too."""
    specs = [ReplicaSpec("solo", model, _cfg())]
    fl = FleetRouter(specs, FleetConfig(start=True, seed=0))
    try:
        rep = fl._replicas["solo"]
        h = fl.submit(SYSTEM, max_new_tokens=300)
        deadline = time.monotonic() + 30
        while not rep.transport.cancel(h):
            assert time.monotonic() < deadline
            time.sleep(0.01)
        assert h.result(timeout=10).finish_reason == "cancelled"
        assert rep.transport.cancel(h) is False
    finally:
        fl.shutdown()
