"""paddle_tpu.generation — paged KV cache, paged decode attention,
continuous batching, sampling, streaming, metrics.

The acceptance oracles (all CPU, conftest forces the backend):

1. paged decode attention numerically matches dense causal
   full-recompute attention — EXACT in fp32 (zero tolerance): padding
   pages/positions contribute exactly zero by construction;
2. continuous-batched greedy generation is token-identical to
   sequential per-request generation — including under forced
   preemption (a page pool sized to thrash);
3. pages are freed on completion: pool utilization returns to zero.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import generation as gen
from paddle_tpu.generation import metrics as gmetrics
from paddle_tpu.profiler.monitor import StatRegistry

from gen_oracle import greedy_oracle  # cross-module memoized oracle
from paddle_tpu.serving.admission import (DeadlineExceededError,
                                          RequestTooLargeError,
                                          ServerBusyError, ServingError)


@pytest.fixture(autouse=True)
def _fresh_generation_stats():
    """generation.* stats are process-global (STAT_ADD parity)."""
    reg = StatRegistry.instance()
    for name in list(reg.stats()):
        if name.startswith(gmetrics.PREFIX):
            reg.get_stat(name).reset()
    yield


@pytest.fixture(scope="module")
def model():
    return gen.TinyCausalLM(vocab_size=48, num_layers=2, num_heads=2,
                            head_dim=8, seed=3)


def _engine(model, *, slots=4, pages=64, page_size=4, start=False, **kw):
    cfg = gen.GenerationConfig(max_decode_slots=slots, num_pages=pages,
                               page_size=page_size, **kw)
    return gen.GenerationEngine(model, cfg, start=start)


# ---------------------------- PagedKVCache ------------------------------


def test_kv_cache_page_table_layout():
    c = gen.PagedKVCache(2, 2, 8, num_pages=8, page_size=4)
    c.allocate("s")
    k = np.arange(2 * 6 * 2 * 8, dtype=np.float32).reshape(2, 6, 2, 8)
    c.append_prefill("s", k, -k)
    assert c.seq_len("s") == 6
    table = c.page_table("s")
    assert len(table) == 2  # ceil(6/4)
    # token t lives at page_table[t//4], row t%4
    for t in range(6):
        np.testing.assert_array_equal(
            c.k_pool[:, table[t // 4], t % 4], k[:, t])
        np.testing.assert_array_equal(
            c.v_pool[:, table[t // 4], t % 4], -k[:, t])


def test_kv_cache_append_crosses_page_boundary():
    c = gen.PagedKVCache(1, 1, 4, num_pages=4, page_size=2)
    c.allocate(0)
    for t in range(5):
        pos = c.append(0, np.full((1, 1, 4), t, np.float32),
                       np.zeros((1, 1, 4), np.float32))
        assert pos == t
    assert len(c.page_table(0)) == 3  # ceil(5/2)
    assert c.pages_in_use == 3


def test_kv_cache_free_returns_pages_and_reuses():
    c = gen.PagedKVCache(1, 1, 4, num_pages=4, page_size=2)
    c.allocate("a")
    c.reserve("a", 6)
    pages_a = set(c.page_table("a"))
    assert c.num_free_pages == 1
    c.free("a")
    assert c.num_free_pages == 4 and c.utilization() == 0.0
    c.allocate("b")
    c.reserve("b", 2)
    # LIFO free list: a just-freed page is reused first
    assert set(c.page_table("b")) <= pages_a


def test_kv_cache_out_of_pages_is_atomic():
    c = gen.PagedKVCache(1, 1, 4, num_pages=2, page_size=2)
    c.allocate(0)
    c.reserve(0, 3)  # 2 pages
    with pytest.raises(gen.OutOfPagesError):
        c.reserve(0, 2)  # needs a 3rd page
    # nothing advanced or leaked on the failed reserve
    assert c.seq_len(0) == 3 and c.num_free_pages == 0


def test_kv_cache_gather_block_tables_pads_with_valid_page():
    c = gen.PagedKVCache(1, 2, 4, num_pages=8, page_size=2)
    for sid, n in (("a", 5), ("b", 1)):
        c.allocate(sid)
        c.reserve(sid, n)
    pt, lens = c.gather_block_tables(["a", "b"])
    assert pt.shape == (2, 3) and pt.dtype == np.int32
    assert lens.tolist() == [5, 1]
    assert (pt >= 0).all() and (pt < 8).all()  # padding is DMA-safe


def test_kv_cache_utilization_stats():
    c = gen.PagedKVCache(1, 1, 4, num_pages=4, page_size=4)
    c.allocate(0)
    c.reserve(0, 5)  # 2 pages, 5 of 8 rows
    s = c.stats()
    assert s["pages_in_use"] == 2 and s["utilization_pct"] == 50.0
    assert s["token_utilization_pct"] == round(100 * 5 / 8, 1)


# ----------------------- paged decode attention -------------------------


def _fill_cache(rng, L, H, D, lens, page_size=4, num_pages=64):
    c = gen.PagedKVCache(L, H, D, num_pages=num_pages, page_size=page_size)
    ks, vs = [], []
    for i, t in enumerate(lens):
        k = rng.standard_normal((L, t, H, D)).astype(np.float32)
        v = rng.standard_normal((L, t, H, D)).astype(np.float32)
        c.allocate(i)
        c.append_prefill(i, k, v)
        ks.append(k)
        vs.append(v)
    return c, ks, vs


@pytest.mark.parametrize("lens", [[7], [13, 5, 24], [1, 9]])
def test_paged_decode_matches_dense_causal_exact_fp32(lens):
    """Acceptance oracle 1: the jnp reference over gathered pages equals
    dense causal full-recompute at the last position, EXACTLY in fp32."""
    rng = np.random.default_rng(0)
    L, H, D = 2, 2, 8
    c, ks, vs = _fill_cache(rng, L, H, D, lens)
    q = rng.standard_normal((len(lens), H, D)).astype(np.float32)
    pt, sl = c.gather_block_tables(range(len(lens)))
    for layer in range(L):
        out = np.asarray(gen.paged_decode_attention_reference(
            q, c.k_pool[layer], c.v_pool[layer], pt, sl))
        for i, t in enumerate(lens):
            # dense causal over the real tokens, query at the last row
            full_q = np.concatenate(
                [np.zeros((t - 1, H, D), np.float32), q[i:i + 1]])
            dense = np.asarray(gen.dense_causal_reference(
                full_q, ks[i][layer], vs[i][layer]))[-1]
            np.testing.assert_array_equal(out[i], dense)


def test_paged_decode_kernel_interpret_matches_reference():
    """The Pallas kernel (interpret mode on CPU) implements the same
    semantics; online softmax reassociates, so small float tolerance."""
    rng = np.random.default_rng(1)
    L, H, D = 1, 2, 128
    c, _, _ = _fill_cache(rng, L, H, D, [13, 5, 24], page_size=8,
                          num_pages=16)
    q = rng.standard_normal((3, H, D)).astype(np.float32)
    pt, sl = c.gather_block_tables([0, 1, 2])
    ref = np.asarray(gen.paged_decode_attention_reference(
        q, c.k_pool[0], c.v_pool[0], pt, sl))
    ker = np.asarray(gen.paged_decode_attention(
        q, c.k_pool[0], c.v_pool[0], pt, sl, use_kernel=True,
        interpret=True))
    np.testing.assert_allclose(ker, ref, atol=2e-5, rtol=2e-5)


def test_paged_decode_empty_sequence_returns_zeros_both_paths():
    """len 0 (all keys masked): both implementations agree on exact
    zeros rather than softmax-of-garbage."""
    rng = np.random.default_rng(5)
    c = gen.PagedKVCache(1, 2, 128, num_pages=4, page_size=8)
    c.allocate(0)
    q = rng.standard_normal((1, 2, 128)).astype(np.float32)
    pt = np.zeros((1, 1), np.int32)
    sl = np.zeros((1,), np.int32)
    ref = np.asarray(gen.paged_decode_attention_reference(
        q, c.k_pool[0], c.v_pool[0], pt, sl))
    ker = np.asarray(gen.paged_decode_attention(
        q, c.k_pool[0], c.v_pool[0], pt, sl, use_kernel=True,
        interpret=True))
    np.testing.assert_array_equal(ref, np.zeros_like(ref))
    np.testing.assert_array_equal(ker, np.zeros_like(ker))


def test_paged_decode_dispatch_defaults_to_reference_on_cpu():
    rng = np.random.default_rng(2)
    c, _, _ = _fill_cache(rng, 1, 1, 8, [3])
    q = rng.standard_normal((1, 1, 8)).astype(np.float32)
    pt, sl = c.gather_block_tables([0])
    auto = np.asarray(gen.paged_decode_attention(
        q, c.k_pool[0], c.v_pool[0], pt, sl))
    ref = np.asarray(gen.paged_decode_attention_reference(
        q, c.k_pool[0], c.v_pool[0], pt, sl))
    np.testing.assert_array_equal(auto, ref)


# ------------------- ops/attention Lq==1 fast path ----------------------


@pytest.mark.parametrize("b,h,lk,d", [
    (1, 1, 5, 8), (2, 3, 17, 16), (1, 2, 128, 64), (2, 1, 256, 32),
])
def test_sdp_decode_fast_path_shape_coverage(b, h, lk, d):
    """Lq == 1 skips the tril build and the flash gate; causal over one
    query row is all-visible, so it must equal the full causal result's
    last row — across shapes including flash-eligible (128-multiple)
    ones with use_flash forced on."""
    from paddle_tpu.ops.attention import scaled_dot_product_attention

    rng = np.random.default_rng(b * 100 + lk)
    q = rng.standard_normal((b, h, lk, d)).astype(np.float32)
    k = rng.standard_normal((b, h, lk, d)).astype(np.float32)
    v = rng.standard_normal((b, h, lk, d)).astype(np.float32)
    full, _ = scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        is_causal=True, use_flash=False)
    fast, _ = scaled_dot_product_attention(
        paddle.to_tensor(q[:, :, -1:]), paddle.to_tensor(k),
        paddle.to_tensor(v), is_causal=True, use_flash=True)
    np.testing.assert_allclose(
        np.asarray(fast.numpy())[:, :, 0], np.asarray(full.numpy())[:, :, -1],
        atol=1e-6, rtol=1e-6)


def test_sdp_decode_fast_path_respects_additive_mask():
    from paddle_tpu.ops.attention import scaled_dot_product_attention

    rng = np.random.default_rng(9)
    q = rng.standard_normal((2, 2, 1, 8)).astype(np.float32)
    k = rng.standard_normal((2, 2, 6, 8)).astype(np.float32)
    v = rng.standard_normal((2, 2, 6, 8)).astype(np.float32)
    mask = np.zeros((2, 1, 1, 6), np.float32)
    mask[:, :, :, -2:] = -1e9  # hide the last two keys
    out, _ = scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        attn_mask=paddle.to_tensor(mask), is_causal=True)
    ref, _ = scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k[:, :, :-2]),
        paddle.to_tensor(v[:, :, :-2]), is_causal=False, use_flash=False)
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               np.asarray(ref.numpy()),
                               atol=1e-6, rtol=1e-6)


# ------------------------------ sampling --------------------------------


def test_sampling_greedy_is_argmax():
    logits = np.array([0.1, 3.0, -1.0, 2.9], np.float32)
    p = gen.SamplingParams()  # temperature 0
    assert p.greedy
    assert gen.sample_token(logits, p, p.make_rng()) == 1


def test_sampling_top_k_restricts_support():
    logits = np.array([5.0, 4.0, -50.0, -60.0], np.float32)
    p = gen.SamplingParams(temperature=1.0, top_k=2, seed=0)
    rng = p.make_rng()
    draws = {gen.sample_token(logits, p, rng) for _ in range(64)}
    assert draws <= {0, 1} and len(draws) == 2


def test_sampling_top_p_nucleus():
    # probs ~ [0.85, 0.10, 0.05]: top_p=0.8 keeps only token 0
    logits = np.log(np.array([0.85, 0.10, 0.05], np.float64))
    p = gen.SamplingParams(temperature=1.0, top_p=0.8, seed=1)
    rng = p.make_rng()
    assert {gen.sample_token(logits, p, rng) for _ in range(32)} == {0}


def test_sampling_seeded_reproducible():
    logits = np.random.default_rng(3).standard_normal(32)
    a = [gen.sample_token(logits, gen.SamplingParams(temperature=1.3,
                                                     top_k=8, seed=7),
                          gen.SamplingParams(seed=7).make_rng())
         for _ in range(4)]
    b = [gen.sample_token(logits, gen.SamplingParams(temperature=1.3,
                                                     top_k=8, seed=7),
                          gen.SamplingParams(seed=7).make_rng())
         for _ in range(4)]
    assert a == b


def test_sampling_param_validation():
    with pytest.raises(ValueError):
        gen.SamplingParams(temperature=-0.1)
    with pytest.raises(ValueError):
        gen.SamplingParams(top_k=-1, temperature=1.0)
    with pytest.raises(ValueError):
        gen.SamplingParams(top_p=1.5, temperature=1.0)


# --------------------------- engine oracles -----------------------------


PROMPTS = [[1, 2, 3], [7, 5], [9, 9, 9, 4, 2], [11]]


def test_continuous_batched_greedy_token_identical_to_sequential(model):
    """Acceptance oracles 2 + 3: batched == sequential, pages freed."""
    eng = _engine(model)
    handles = [eng.submit(p, max_new_tokens=12) for p in PROMPTS]
    eng.run_until_idle()
    for h, p in zip(handles, PROMPTS):
        res = h.result(timeout=5)
        assert res.token_ids == greedy_oracle(model, p, 12)
        assert res.finish_reason == "length"
    # oracle 3: every page returned to the pool
    assert eng.cache.utilization() == 0.0
    assert eng.cache.num_free_pages == eng.cache.num_pages
    eng.shutdown()


def test_generation_token_identical_under_forced_preemption(model):
    """A pool too small for 4 concurrent sequences forces recompute
    preemption — which must not change a single token."""
    eng = _engine(model, pages=9)
    handles = [eng.submit(p, max_new_tokens=12) for p in PROMPTS]
    eng.run_until_idle()
    results = [h.result(timeout=5) for h in handles]
    for res, p in zip(results, PROMPTS):
        assert res.token_ids == greedy_oracle(model, p, 12)
    assert sum(r.preemptions for r in results) > 0  # the pool did thrash
    assert eng.metrics.snapshot()["generation.preempted_total"] > 0
    assert eng.cache.utilization() == 0.0
    eng.shutdown()


def test_generation_one_slot_serializes_but_tokens_identical(model):
    """A 1-slot engine serves the same prompts strictly one at a time;
    batch composition is invisible to results."""
    eng = _engine(model, slots=1, pages=16)
    handles = [eng.submit(p, max_new_tokens=6) for p in PROMPTS]
    eng.run_until_idle()
    for h, p in zip(handles, PROMPTS):
        assert h.result(timeout=5).token_ids == greedy_oracle(model, p, 6)
    eng.shutdown()


def test_generation_stop_tokens_and_finish_reasons(model):
    eng = _engine(model)
    # discover the greedy stream, then stop on its 3rd token
    free = greedy_oracle(model, [1, 2, 3], 8)
    stop = free[2]
    h = eng.submit([1, 2, 3], max_new_tokens=8, stop_tokens=(stop,))
    eng.run_until_idle()
    res = h.result(timeout=5)
    assert res.finish_reason == "stop"
    assert res.token_ids == free[:2]  # stop token itself excluded
    assert eng.cache.utilization() == 0.0
    eng.shutdown()


def test_generation_max_new_tokens_zero_and_exact(model):
    eng = _engine(model)
    h0 = eng.submit([1, 2], max_new_tokens=0)
    h3 = eng.submit([1, 2], max_new_tokens=3)
    eng.run_until_idle()
    assert h0.result(timeout=5).token_ids == []
    assert h0.result().finish_reason == "length"
    assert len(h3.result(timeout=5).token_ids) == 3
    eng.shutdown()


def test_generation_streaming_tokens_match_result(model):
    eng = _engine(model, start=True)
    try:
        h = eng.submit([5, 6, 7], max_new_tokens=8)
        streamed = list(h.tokens(timeout=30))
        assert streamed == h.result(timeout=5).token_ids
        assert len(streamed) == 8
    finally:
        eng.shutdown()


def test_generation_busy_rejection_typed(model):
    eng = _engine(model, queue_depth=2)  # not started: queue fills
    eng.submit([1], max_new_tokens=1)
    eng.submit([2], max_new_tokens=1)
    with pytest.raises(ServerBusyError):
        eng.submit([3], max_new_tokens=1)
    stats = eng.metrics.snapshot()
    assert stats["generation.rejected_busy"] == 1
    eng.run_until_idle()
    eng.shutdown()


def test_generation_prompt_too_large_typed(model):
    eng = _engine(model, pages=2, page_size=4)
    with pytest.raises(RequestTooLargeError):
        eng.submit(list(range(1, 20)), max_new_tokens=1)  # > 8 rows
    eng.shutdown()


def test_generation_deadline_rejection_typed(model):
    eng = _engine(model)  # not started
    h = eng.submit([1, 2], max_new_tokens=4, timeout_ms=1.0)
    time.sleep(0.02)  # lapse in queue
    eng.step()
    with pytest.raises(DeadlineExceededError):
        h.result(timeout=1)
    # the stream surfaces the same typed error
    with pytest.raises(DeadlineExceededError):
        list(h.tokens(timeout=1))
    assert eng.metrics.snapshot()["generation.rejected_deadline"] >= 1
    eng.shutdown()


def test_generation_shutdown_rejects_queued(model):
    eng = _engine(model, queue_depth=8)
    h = eng.submit([1, 2], max_new_tokens=4)
    eng.shutdown()
    with pytest.raises(ServingError):
        h.result(timeout=1)
    with pytest.raises(ServingError):
        eng.submit([3], max_new_tokens=1)


def test_generation_temperature_sampling_deterministic_per_seed(model):
    eng = _engine(model)
    mk = lambda: gen.SamplingParams(temperature=0.9, top_k=10, top_p=0.9,
                                    seed=42)
    h1 = eng.submit([3, 1], max_new_tokens=6, sampling=mk())
    h2 = eng.submit([3, 1], max_new_tokens=6, sampling=mk())
    eng.run_until_idle()
    assert h1.result(timeout=5).token_ids == h2.result(timeout=5).token_ids
    eng.shutdown()


def test_generation_metrics_and_snapshot_export(model, tmp_path):
    eng = _engine(model)
    handles = [eng.submit(p, max_new_tokens=5) for p in PROMPTS[:2]]
    eng.run_until_idle()
    for h in handles:
        h.result(timeout=5)
    stats = eng.metrics.snapshot()
    assert stats["generation.requests_total"] == 2
    assert stats["generation.finished_total"] == 2
    assert stats["generation.tokens_total"] == 10
    assert stats["generation.prefill_tokens_total"] == \
        len(PROMPTS[0]) + len(PROMPTS[1])
    assert stats["generation.steps_total"] >= 4
    # stats_snapshot: BENCH-style JSON artifact (satellite)
    out = tmp_path / "gen_stats.json"
    snap = StatRegistry.instance().stats_snapshot("generation.",
                                                  path=str(out))
    assert set(snap) == {"ts", "stats"}
    assert all(k.startswith("generation.") for k in snap["stats"])
    import json

    on_disk = json.loads(out.read_text())
    assert on_disk["stats"] == snap["stats"]
    eng.shutdown()


def test_generation_record_event_spans(model):
    """enable_profile-style runs see generation internals."""
    from paddle_tpu import profiler

    eng = _engine(model)
    profiler.start_profiler()
    try:
        eng.submit([1, 2], max_new_tokens=3)
        eng.run_until_idle()
    finally:
        report = profiler.stop_profiler()
    for span in ("generation::prefill", "generation::decode_step",
                 "generation::sample"):
        assert span in report
    eng.shutdown()


def test_generation_tight_pool_all_sequences_hit_boundary_together(model):
    """Review-found corner: every sequence crosses a page boundary in
    the SAME step with zero free pages.  Single-victim preemption with
    the shortfall recomputed after each must let the survivors (and
    later the victims) finish — no request may be hard-failed, and every
    preemption must be counted."""
    rng = np.random.default_rng(4)
    prompts = [rng.integers(1, 40, 15).tolist() for _ in range(4)]
    eng = _engine(model, slots=4, pages=4, page_size=16)
    handles = [eng.submit(p, max_new_tokens=8) for p in prompts]
    eng.run_until_idle()
    results = [h.result(timeout=5) for h in handles]  # none may raise
    for res, p in zip(results, prompts):
        assert res.token_ids == greedy_oracle(model, p, 8)
    assert sum(r.preemptions for r in results) > 0
    stats = eng.metrics.snapshot()
    assert stats["generation.preempted_total"] == \
        sum(r.preemptions for r in results)
    assert eng.cache.utilization() == 0.0
    eng.shutdown()


def test_generation_max_positions_typed_rejection(model):
    eng = _engine(model)
    assert model.max_positions == 512
    with pytest.raises(RequestTooLargeError):
        eng.submit([1] * 500, max_new_tokens=20)
    eng.shutdown()


def test_generation_worker_survives_model_error(model):
    """A model exception must fail the affected handles with the real
    error (batch-fails-as-a-unit, DynamicBatcher semantics) — never
    strand clients on a dead worker thread."""

    class Poisoned:
        num_layers = model.num_layers
        num_heads = model.num_heads
        head_dim = model.head_dim
        vocab_size = model.vocab_size

        def prefill(self, tokens):
            raise RuntimeError("poisoned prefill")

        def decode(self, tokens, positions, attend):
            raise RuntimeError("poisoned decode")

    eng = gen.GenerationEngine(
        Poisoned(), gen.GenerationConfig(max_decode_slots=2, num_pages=16,
                                         page_size=4), start=True)
    try:
        h1 = eng.submit([1, 2], max_new_tokens=4)
        with pytest.raises(RuntimeError, match="poisoned"):
            h1.result(timeout=10)
        # the worker is still alive and keeps draining with typed errors
        h2 = eng.submit([3], max_new_tokens=2)
        with pytest.raises(RuntimeError, match="poisoned"):
            h2.result(timeout=10)
    finally:
        eng.shutdown()
    assert eng.cache.utilization() == 0.0


def test_generation_background_worker_end_to_end(model):
    """Worker-thread path: submit from multiple client threads, no
    manual stepping anywhere."""
    import concurrent.futures as cf

    eng = _engine(model, start=True)
    try:
        with cf.ThreadPoolExecutor(4) as pool:
            futs = [pool.submit(
                lambda p=p: eng.submit(p, max_new_tokens=8).result(
                    timeout=60)) for p in PROMPTS]
            results = [f.result(timeout=60) for f in futs]
        for res, p in zip(results, PROMPTS):
            assert res.token_ids == greedy_oracle(model, p, 8)
    finally:
        eng.shutdown()
    assert eng.cache.utilization() == 0.0
