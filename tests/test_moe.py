"""MoE / expert-parallel tests (meta_parallel/moe_layer.py).

Eager correctness (routing respects capacity, combine weights normalize,
gradient flows), then loss-parity of the expert-parallel compiled path
against single-device eager on the virtual CPU mesh.
"""
import numpy as np
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.meta_parallel.moe_layer import MoELayer
from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny
from paddle_tpu.parallel.env import build_mesh
from paddle_tpu.parallel.hybrid import CompiledTrainStep


def _np(t):
    return np.asarray(t._data)


def test_moe_eager_forward_and_grad():
    paddle.seed(0)
    layer = MoELayer(hidden_size=16, ffn_hidden=32, num_experts=4)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 8, 16).astype(np.float32))
    x.stop_gradient = False
    out = layer(x)
    assert list(out.shape) == [2, 8, 16]
    assert layer.aux_loss is not None
    assert np.isfinite(float(_np(layer.aux_loss)))
    total = paddle.mean(out) + paddle.scale(layer.aux_loss, 0.01)
    total.backward()
    for name in ("gate_weight", "w1", "w2"):
        g = getattr(layer, name).grad
        assert g is not None and np.isfinite(np.asarray(g._data)).all(), name


def test_moe_expert_params_annotated():
    layer = MoELayer(hidden_size=8, ffn_hidden=16, num_experts=4)
    assert tuple(layer.w1.dist_spec)[0] == "expert"
    # gate is replicated (no dist_spec annotation)
    assert getattr(layer.gate_weight, "dist_spec", None) is None


def test_moe_capacity_drops_overflow():
    """With capacity factor tiny, combine rows of dropped tokens are 0 and
    outputs for those tokens are 0 (residual carries them)."""
    paddle.seed(1)
    layer = MoELayer(hidden_size=8, ffn_hidden=16, num_experts=2,
                     capacity_factor=0.01)
    x = paddle.to_tensor(np.random.RandomState(1)
                         .randn(1, 32, 8).astype(np.float32))
    out = layer(x)
    arr = _np(out).reshape(32, 8)
    # capacity = max(ceil(2*32/2*0.01), 4) = 4 slots/expert -> most dropped
    dropped = np.sum(np.all(arr == 0.0, axis=1))
    assert dropped >= 32 - 2 * 4 * 2


def test_moe_gpt_trains_eager():
    paddle.seed(2)
    cfg = gpt_tiny()
    cfg.num_experts = 4
    model = GPTForPretraining(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    rng = np.random.RandomState(2)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (4, 16))
                           .astype(np.int32))
    losses = []
    for _ in range(4):
        loss = model.loss(ids, ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(_np(loss)))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_moe_expert_parallel_compiled_parity():
    """dp x ep compiled MoE-GPT step vs single-device eager: same loss at
    step 1 and finite after an update."""
    paddle.seed(3)
    cfg = gpt_tiny()
    cfg.num_experts = 4
    cfg.dropout = 0.0
    model = GPTForPretraining(cfg)
    rng = np.random.RandomState(3)
    ids = rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
    t_ids = paddle.to_tensor(ids)

    with paddle.no_grad():
        eager_loss = float(_np(model.loss(t_ids, t_ids)))

    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    mesh = build_mesh({"data": 2, "expert": 2})
    tr = CompiledTrainStep(model, lambda m, i, l: m.loss(i, l), opt, mesh)
    l1 = float(_np(tr.step(t_ids, t_ids)))
    # routing/copy order is identical (same params, same tokens): the
    # sharded step must reproduce the eager loss
    np.testing.assert_allclose(l1, eager_loss, rtol=2e-3)
    l2 = float(_np(tr.step(t_ids, t_ids)))
    assert np.isfinite(l2) and l2 < l1
