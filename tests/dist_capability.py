"""CPU-backend multiprocess-collectives capability probe.

The multi-process DP tests (test_dist_multiprocess launch/spawn,
test_preemption_drill) exercise real 2-process jax.distributed
collectives.  The stock CPU backend cannot execute them — every jitted
cross-process computation dies with "Multiprocess computations aren't
implemented on the CPU backend" — which left three KNOWN reds in every
tier-1 log since the seed (verified identical on a clean HEAD worktree,
CHANGES.md PR 3/8).  Rather than memorizing which reds are expected,
this probe MEASURES the capability once per test session: it forks a
2-process world running one jitted psum (dist_collective_probe.py, the
exact trainer mechanism) and the dependent tests carry
``pytest.mark.skipif(not multiprocess_collectives_available(), ...)`` —
green logs on backends without the capability, full coverage on
backends with it (multi-host TPU/GPU pods), and a loud FAILURE (not a
skip) if a backend claims the capability but the DP contract breaks.
"""
import os
import socket
import subprocess
import sys
import time

_RESULT = None
_PROBE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "dist_collective_probe.py")

SKIP_REASON = ("backend cannot execute multiprocess collectives "
               "(probed: 2-process jitted psum failed — the known "
               "CPU-backend limitation)")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_SUBPROC_RESULT = None

SUBPROC_SKIP_REASON = ("environment cannot spawn socketpair-connected "
                       "subprocesses (probed: fd-inheriting child "
                       "echo failed — sandboxed or fork-less host)")


def subprocess_replicas_available(timeout=30.0):
    """True iff this host can run SubprocTransport replicas: spawn a
    python child with an inherited UNIX socketpair fd and talk over
    it.  Same probe-once-per-process pattern as the collectives probe
    below — the disagg tests skip fast and clean where fork/sockets
    are unavailable, with a cheap echo child (never a full jax
    import) paying the probe."""
    global _SUBPROC_RESULT
    if _SUBPROC_RESULT is not None:
        return _SUBPROC_RESULT
    ok = False
    try:
        parent, child = socket.socketpair()
        try:
            proc = subprocess.Popen(
                [sys.executable, "-c",
                 "import socket, sys; "
                 "s = socket.socket(fileno=int(sys.argv[1])); "
                 "s.sendall(b'SUBPROC_OK'); s.close()",
                 str(child.fileno())],
                pass_fds=(child.fileno(),))
            child.close()
            parent.settimeout(timeout)
            ok = parent.recv(16) == b"SUBPROC_OK"
            proc.wait(timeout=timeout)
        finally:
            parent.close()
    except Exception:
        ok = False
    _SUBPROC_RESULT = ok
    return ok


def multiprocess_collectives_available(timeout=90.0):
    """True iff a 2-process jax.distributed psum actually executes on
    this backend.  Probed at most once per process (both dist test
    modules share this module, so one tier-1 collection pays one
    probe); failure OR timeout reads as unavailable."""
    global _RESULT
    if _RESULT is not None:
        return _RESULT
    master = f"127.0.0.1:{_free_port()}"
    procs = []
    ok = True
    try:
        for rank in range(2):
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
            procs.append(subprocess.Popen(
                [sys.executable, _PROBE, master, "2", str(rank)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                env=env, cwd=os.path.dirname(_PROBE)))
        deadline = time.time() + timeout
        for p in procs:
            remaining = max(1.0, deadline - time.time())
            try:
                out, _ = p.communicate(timeout=remaining)
            except subprocess.TimeoutExpired:
                ok = False
                break
            if p.returncode != 0 or b"COLLECTIVES_OK" not in out:
                ok = False
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    _RESULT = ok
    return ok
