"""paddle.metric value goldens (Accuracy top-k, Precision, Recall, Auc).

Ref: python/paddle/metric/metrics.py:38-593. Expected values are
computed by hand / closed form (AUC via the Mann-Whitney rank formula),
independent of the streaming-histogram implementations under test.
"""
import numpy as np

import paddle_tpu as paddle


def test_accuracy_topk():
    m = paddle.metric.Accuracy(topk=(1, 2))
    preds = paddle.to_tensor(np.array([
        [0.1, 0.7, 0.2],   # top1=1 top2={1,2}
        [0.5, 0.3, 0.2],   # top1=0 top2={0,1}
        [0.2, 0.3, 0.5],   # top1=2 top2={2,1}
    ], np.float32))
    labels = paddle.to_tensor(np.array([[1], [1], [0]], np.int64))
    correct = m.compute(preds, labels)
    m.update(correct)
    acc1, acc2 = m.accumulate()
    assert abs(acc1 - 1 / 3) < 1e-6   # only row 0 top-1 correct
    assert abs(acc2 - 2 / 3) < 1e-6   # rows 0,1 within top-2
    # streaming: second batch all correct shifts the average
    preds2 = paddle.to_tensor(np.array([[0.9, 0.1, 0.0]], np.float32))
    labels2 = paddle.to_tensor(np.array([[0]], np.int64))
    m.update(m.compute(preds2, labels2))
    acc1b, _ = m.accumulate()
    assert abs(acc1b - 2 / 4) < 1e-6


def test_precision_recall():
    # binary preds (prob of positive); threshold 0.5
    preds = np.array([0.9, 0.8, 0.2, 0.6, 0.1], np.float32)
    labels = np.array([1, 0, 1, 1, 0], np.int64)
    # predicted positive: {0,1,3} -> TP={0,3}, FP={1}; FN={2}
    p = paddle.metric.Precision()
    p.update(preds, labels)
    assert abs(p.accumulate() - 2 / 3) < 1e-6
    r = paddle.metric.Recall()
    r.update(preds, labels)
    assert abs(r.accumulate() - 2 / 3) < 1e-6


def test_auc_against_rank_formula():
    rng = np.random.RandomState(0)
    n = 400
    labels = rng.randint(0, 2, n)
    # informative but noisy scores
    preds = np.clip(labels * 0.4 + rng.rand(n) * 0.6, 0, 1)

    m = paddle.metric.Auc()
    m.update(np.stack([1 - preds, preds], 1).astype(np.float32),
             labels.reshape(-1, 1))
    got = m.accumulate()

    # exact AUC: Mann-Whitney U / (n_pos * n_neg), ties get half credit
    pos = preds[labels == 1]
    neg = preds[labels == 0]
    wins = (pos[:, None] > neg[None, :]).sum()
    ties = (pos[:, None] == neg[None, :]).sum()
    exact = (wins + 0.5 * ties) / (len(pos) * len(neg))
    assert abs(got - exact) < 2e-3  # histogram discretization error only


def test_auc_streaming_equals_one_shot():
    rng = np.random.RandomState(1)
    preds = rng.rand(100).astype(np.float32)
    labels = rng.randint(0, 2, 100)
    one = paddle.metric.Auc()
    one.update(np.stack([1 - preds, preds], 1), labels.reshape(-1, 1))
    two = paddle.metric.Auc()
    for lo in range(0, 100, 10):
        sl = slice(lo, lo + 10)
        two.update(np.stack([1 - preds[sl], preds[sl]], 1),
                   labels[sl].reshape(-1, 1))
    assert abs(one.accumulate() - two.accumulate()) < 1e-9
