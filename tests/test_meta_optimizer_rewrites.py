"""Meta-optimizer program-rewrite assertions (the reference's key dist-test
trick: fleet_meta_optimizer_base.py builds a program, applies
fleet.minimize with a strategy, then asserts on the rewritten op list —
no devices needed).  VERDICT r1 item 9.

Ref: test_fleet_sharding_meta_optimizer.py, strategy_compiler.py:1.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static


def _build_program(hidden=16):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, hidden])
        y = static.nn.fc(x, hidden)
        y = static.nn.relu(y)
        out = static.nn.fc(y, 1)
        loss = static.nn.mean(out * out)
    return main, startup, loss


def _fleet_minimize(strategy_flags, loss, opt=None, startup=None,
                    ps_mode=False):
    import os

    from paddle_tpu.distributed.fleet.distributed_strategy import (
        DistributedStrategy,
    )
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        apply_meta_optimizers,
    )
    from paddle_tpu.distributed.fleet import Fleet

    strategy = DistributedStrategy()
    for k, v in strategy_flags.items():
        setattr(strategy, k, v)
    f = Fleet()
    saved = {}
    if ps_mode:
        # PS role env (the role maker gates the PS meta-optimizer)
        for k, v in {"TRAINING_ROLE": "TRAINER", "PADDLE_TRAINER_ID": "0",
                     "PADDLE_PSERVER_ENDPOINTS": "127.0.0.1:1",
                     "PADDLE_TRAINERS_NUM": "1"}.items():
            saved[k] = os.environ.get(k)
            os.environ[k] = v
    try:
        f.init(is_collective=not ps_mode, strategy=strategy)
        opt = opt or paddle.optimizer.Momentum(learning_rate=0.1,
                                               momentum=0.9)
        return strategy, apply_meta_optimizers(opt, strategy, loss, startup,
                                               f)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---- tensor parallel: specs from call sites, not guessed ----

def test_static_split_column_then_row_specs():
    """collective.split call sites attach the correct specs regardless of
    layer order (the r1 alternation heuristic would mislabel col,col)."""
    from jax.sharding import PartitionSpec as P

    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4, 8])
            h1 = paddle.distributed.split(x, (8, 16), "linear", axis=1,
                                          gather_out=False)
            h2 = paddle.distributed.split(h1, (16, 8), "linear", axis=0)
            loss = static.nn.mean(h2 * h2)
            _fleet_minimize(
                {"tensor_parallel": True,
                 "tensor_parallel_configs": {"tensor_parallel_degree": 2}},
                loss)
        block = main.global_block()
        specs = {n: v.dist_spec for n, v in block.vars.items()
                 if getattr(v, "dist_spec", None) is not None
                 and v.is_parameter and len(v.shape) == 2}
        assert len(specs) == 2
        col = [s for s in specs.values() if s == P(None, "model")]
        row = [s for s in specs.values() if s == P("model", None)]
        assert len(col) == 1 and len(row) == 1
        types = [op.type for op in block.ops]
        assert "c_identity" in types       # column input marker
        assert "c_allreduce_sum" in types  # row output reduce
        assert "c_broadcast" in types      # input broadcast at start
        # rewritten program still trains
        exe = static.Executor()
        exe.run(startup)
        xv = np.random.RandomState(0).randn(4, 8).astype("float32")
        l0 = exe.run(main, feed={"x": xv}, fetch_list=[loss])[0]
        for _ in range(5):
            l1 = exe.run(main, feed={"x": xv}, fetch_list=[loss])[0]
        assert float(l1) < float(l0)
    finally:
        paddle.disable_static()


def test_tp_without_call_sites_does_not_guess():
    paddle.enable_static()
    try:
        main, startup, loss = _build_program()
        with static.program_guard(main, startup):
            _fleet_minimize(
                {"tensor_parallel": True,
                 "tensor_parallel_configs": {"tensor_parallel_degree": 2}},
                loss)
        block = main.global_block()
        assert not any(getattr(v, "dist_spec", None) is not None
                       for v in block.vars.values() if v.is_parameter)
        assert "c_broadcast" not in [op.type for op in block.ops]
    finally:
        paddle.disable_static()


def test_tp_degree_divisibility_validated():
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4, 6])
            h = paddle.distributed.split(x, (6, 9), "linear", axis=1)
            loss = static.nn.mean(h * h)
            with pytest.raises(ValueError, match="not divisible"):
                _fleet_minimize(
                    {"tensor_parallel": True,
                     "tensor_parallel_configs": {"tensor_parallel_degree": 2}},
                    loss)
    finally:
        paddle.disable_static()


def test_static_vocab_parallel_embedding():
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            ids = static.data("ids", [4, 3], dtype="int64")
            emb = paddle.distributed.split(ids, (32, 8), "embedding")
            loss = static.nn.mean(emb * emb)
        types = [op.type for op in main.global_block().ops]
        assert "c_embedding" in types and "c_allreduce_sum" in types
        exe = static.Executor()
        exe.run(startup)
        out = exe.run(main,
                      feed={"ids": np.array([[0, 1, 2]] * 4, np.int64)},
                      fetch_list=[emb])
        assert out[0].shape == (4, 3, 8)
    finally:
        paddle.disable_static()


# ---- StrategyCompiler ordering / exclusion ----

def test_strategy_compiler_orders_and_stacks():
    from paddle_tpu.distributed.fleet.distributed_strategy import (
        DistributedStrategy,
    )
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        META_OPTIMIZERS, StrategyCompiler, AMPOptimizer, RecomputeOptimizer,
        ShardingOptimizer,
    )

    strategy = DistributedStrategy()
    strategy.amp = True
    strategy.recompute = True
    strategy.sharding = True
    opt = paddle.optimizer.Momentum(learning_rate=0.1)
    metas = [cls(opt) for cls in META_OPTIMIZERS]
    chain = StrategyCompiler().generate_optimizer(None, None, opt, strategy,
                                                  metas)
    kinds = [type(m) for m in chain]
    assert kinds.index(AMPOptimizer) < kinds.index(RecomputeOptimizer) \
        < kinds.index(ShardingOptimizer)


def test_strategy_compiler_sharding_disables_dgc():
    from paddle_tpu.distributed.fleet.distributed_strategy import (
        DistributedStrategy,
    )
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        META_OPTIMIZERS, StrategyCompiler, DGCOptimizer, ShardingOptimizer,
    )

    strategy = DistributedStrategy()
    strategy.sharding = True
    strategy.dgc = True
    strategy.fp16_allreduce = True
    opt = paddle.optimizer.Momentum(learning_rate=0.1)
    metas = [cls(opt) for cls in META_OPTIMIZERS]
    chain = StrategyCompiler().generate_optimizer(None, None, opt, strategy,
                                                  metas)
    kinds = [type(m) for m in chain]
    assert ShardingOptimizer in kinds
    assert DGCOptimizer not in kinds
    assert strategy.dgc is False            # _disable_strategy parity
    assert strategy.fp16_allreduce is False


def test_sharding_rewrite_op_list():
    """test_fleet_sharding_meta_optimizer.py parity: c_broadcast +
    c_reduce_sum inserted before the update ops."""
    paddle.enable_static()
    try:
        main, startup, loss = _build_program()
        with static.program_guard(main, startup):
            _fleet_minimize(
                {"sharding": True,
                 "sharding_configs": {"sharding_degree": 2}}, loss)
        types = [op.type for op in main.global_block().ops]
        assert "c_broadcast" in types
        assert "c_reduce_sum" in types
        # broadcast/reduce come before the first update op
        first_update = min(i for i, t in enumerate(types) if t == "momentum")
        assert max(i for i, t in enumerate(types)
                   if t in ("c_broadcast", "c_reduce_sum")) < first_update
    finally:
        paddle.disable_static()


def test_tp_broadcast_keeps_partial_feed_prunable():
    """Input broadcasts must not force unfed vars: fetching only the
    forward output with label unfed still runs (broadcast + loss ops
    prune away)."""
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4, 8])
            label = static.data("label", [4, 1])
            h = paddle.distributed.split(x, (8, 4), "linear", axis=1)
            out = static.nn.fc(h, 1)
            diff = out - label
            loss = static.nn.mean(diff * diff)
            _fleet_minimize(
                {"tensor_parallel": True,
                 "tensor_parallel_configs": {"tensor_parallel_degree": 2}},
                loss, opt=_NoMinimizeOpt())
        exe = static.Executor()
        exe.run(startup)
        res = exe.run(main, feed={"x": np.ones((4, 8), np.float32)},
                      fetch_list=[out])
        assert res[0].shape == (4, 1)
    finally:
        paddle.disable_static()


class _NoMinimizeOpt:
    """Inner optimizer stub: no update ops, so the program stays
    inference-shaped (partial feed is meaningful)."""

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return None, []


def test_parameter_server_rewrite_op_list():
    """pscore parity: a_sync strategy replaces local update ops with
    send(grad)/recv(param) and plants listen_and_serv in startup."""
    paddle.enable_static()
    try:
        main, startup, loss = _build_program()
        with static.program_guard(main, startup):
            _fleet_minimize({"a_sync": True}, loss, startup=startup,
                            ps_mode=True)
        types = [op.type for op in main.global_block().ops]
        assert "send" in types and "recv" in types
        assert "momentum" not in types  # update ops dropped
        assert "listen_and_serv" in [op.type
                                     for op in startup.global_block().ops]
    finally:
        paddle.disable_static()


def test_parameter_server_program_trains_against_live_server():
    """The rewritten program's send/recv ops drive a real PSServer via
    host callbacks: params update server-side only."""
    import socket

    from paddle_tpu.distributed.ps.service import PSServer, PSClient
    from paddle_tpu.distributed.ps.communicator import Communicator
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        parameter_server_optimizer as pso,
    )

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    ep = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    server = PSServer(ep, trainers=1)
    server.start()
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4, 8])
            y = static.nn.fc(x, 1)
            loss = static.nn.mean(y * y)
            _fleet_minimize(
                {"a_sync": True}, loss,
                opt=paddle.optimizer.SGD(learning_rate=0.1),
                startup=startup, ps_mode=True)
        exe = static.Executor()
        exe.run(startup)

        client = PSClient([ep])
        client.ping()
        comm = Communicator(client, mode="async", n_workers=1)
        pso.attach_communicator(comm)
        # seed server tables from the initialized scope
        from paddle_tpu.static.executor import global_scope

        block = main.global_block()
        for n, v in block.vars.items():
            if v.is_parameter:
                val = np.asarray(global_scope().get(n))
                client.create_dense_table(n, val.shape, lr=0.1)
                client.set_dense(n, val)

        xv = np.random.RandomState(0).randn(4, 8).astype("float32")
        l0 = float(exe.run(main, feed={"x": xv}, fetch_list=[loss])[0])
        for _ in range(8):
            l1 = float(exe.run(main, feed={"x": xv}, fetch_list=[loss])[0])
        assert l1 < l0, (l0, l1)
        # and the fresh values live server-side
        w_server = client.pull_dense(
            [n for n, v in block.vars.items() if v.is_parameter
             and len(v.shape) == 2][0])
        assert np.isfinite(w_server).all()
        client.close()
    finally:
        pso.attach_communicator(None)
        paddle.disable_static()
        server.shutdown()
