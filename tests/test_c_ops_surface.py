"""Audit the _C_ops binding table: every alias must resolve to a real
callable; a few spot ops must compute; absent ops raise with rationale.

Also measures coverage against the reference's 286 top-level *_op.cc
names so the surface can only grow (ratchet assert).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import _C_ops


def test_every_alias_resolves():
    bad = []
    for name in _C_ops.op_names():
        try:
            fn = getattr(_C_ops, name)
        except Exception as e:
            bad.append((name, repr(e)))
            continue
        if not callable(fn):
            bad.append((name, "not callable"))
    assert not bad, f"unresolvable _C_ops aliases: {bad}"


def test_spot_ops_compute():
    x = paddle.to_tensor(np.array([[1.0, 2.0]], np.float32))
    np.testing.assert_allclose(
        np.asarray(_C_ops.elementwise_add(x, x)._data), [[2.0, 4.0]])
    np.testing.assert_allclose(
        np.asarray(_C_ops.reduce_sum(x)._data), 3.0)
    out = _C_ops.softmax(x)
    assert abs(float(np.asarray(out._data).sum()) - 1.0) < 1e-5


def test_absent_ops_raise_with_rationale():
    with pytest.raises(NotImplementedError) as ei:
        _C_ops.pull_box_sparse
    assert "BoxPS" in str(ei.value)
    with pytest.raises(AttributeError):
        _C_ops.no_such_op_xyz


def test_surface_coverage_ratchet():
    """served + documented-absent must cover >= 95% of the reference's
    top-level op names (the rest are trivially-aliased variants)."""
    import os

    ref_list = "/root/reference/paddle/fluid/operators"
    if not os.path.isdir(ref_list):
        pytest.skip("reference tree unavailable")
    names = sorted(
        f[:-6] for f in os.listdir(ref_list) if f.endswith("_op.cc"))
    served = set(_C_ops.op_names())
    absent = set(_C_ops.absent_ops())
    extra_served = {  # names implemented under different entry points
        "assert": "static.Assert", "print": "static.Print",
        "recurrent": "static.StaticRNN", "while": "static.nn.while_loop",
        "conditional_block": "static.nn.cond",
        "select_input": "static.select_input",
        "select_output": "static.select_output",
        "save": "static.io.save", "load": "static.io.load",
        "save_combine": "static.io.save", "load_combine": "static.io.load",
        "run_program": "jit.TranslatedLayer", "queue_generator":
        "queue_generator", "enqueue": "enqueue", "dequeue": "dequeue",
        "is_empty": "is_empty", "nop": "nop",
        "fake_quantize": "quant.qat", "fake_dequantize": "quant.qat",
        "empty": "empty", "activation": "nn.functional",
        "conv": "nn.functional.conv2d", "pool": "nn.functional.max_pool2d",
        "pool_with_index": "max_pool2d_with_index",
        "conv_transpose": "nn.functional.conv2d_transpose",
        "detection_map": "vision.ops", "py_layer": "autograd.PyLayer",
        "sync_batch_norm": "nn.SyncBatchNorm", "rnn": "nn.RNN",
        "gru": "nn.GRU", "lstm": "nn.LSTM", "gru_unit": "nn.GRUCell",
        "lstm_unit": "nn.LSTMCell", "cudnn_lstm": "nn.LSTM",
        "set_value": "Tensor.__setitem__", "fc": "static.nn.fc",
        "isfinite": "isfinite", "expand": "expand", "expand_as": "expand_as",
        "fill": "full", "flatten": "flatten", "one_hot": "one_hot",
        "top_k": "topk", "reshape": "reshape", "transpose": "transpose",
        "squeeze": "squeeze", "unsqueeze": "unsqueeze", "slice": "slice",
        "lookup_table": "embedding", "minus": "subtract",
    }
    covered = 0
    missing = []
    for n in names:
        if (n in served or n in absent or n in extra_served
                or n + "_v2" in served or n + "2" in served):
            covered += 1
        else:
            missing.append(n)
    frac = covered / len(names)
    assert frac >= 1.0, (
        f"op-surface coverage regressed: {frac:.2%}; missing {missing}")
