"""Static control-flow tests: cond / while_loop / switch_case / case lower to
lax primitives inside the compiled block.

Ref: operators/controlflow/ + fluid/layers/control_flow.py tests
(test_cond.py, test_while_loop_op.py in the reference suite).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.static as static


def _run(main, startup, feed, fetch):
    exe = static.Executor()
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=fetch)


def test_cond_branches():
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data(name="x", shape=[4], dtype="float32")
            p = static.data(name="p", shape=[1], dtype="float32")
            out = static.nn.cond(p, lambda: x * 2.0, lambda: x - 1.0)
        xv = np.arange(4, dtype=np.float32)
        (hi,) = _run(main, startup, {"x": xv, "p": np.ones(1, np.float32)},
                     [out])
        np.testing.assert_allclose(hi, xv * 2)
        (lo,) = _run(main, startup, {"x": xv, "p": np.zeros(1, np.float32)},
                     [out])
        np.testing.assert_allclose(lo, xv - 1)
    finally:
        paddle.disable_static()


def test_cond_captures_params():
    """Branches that close over a parameter created outside the branch."""
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data(name="x", shape=[2, 3], dtype="float32")
            y = static.nn.fc(x, size=3)
            p = static.data(name="p", shape=[1], dtype="float32")
            out = static.nn.cond(p, lambda: y + 1.0, lambda: y * 0.0)
        xv = np.ones((2, 3), np.float32)
        (a,) = _run(main, startup, {"x": xv, "p": np.ones(1, np.float32)},
                    [out])
        (b,) = _run(main, startup, {"x": xv, "p": np.zeros(1, np.float32)},
                    [out])
        np.testing.assert_allclose(b, np.zeros((2, 3)), atol=1e-6)
        assert np.all(a != 0)  # fc + 1 with nonzero bias-free weights
    finally:
        paddle.disable_static()


def test_while_loop_counts():
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            i = static.data(name="i", shape=[1], dtype="float32")
            s = static.data(name="s", shape=[1], dtype="float32")
            limit = static.data(name="limit", shape=[1], dtype="float32")
            iv, sv = static.nn.while_loop(
                lambda i, s: i < limit,
                lambda i, s: [i + 1.0, s + i],
                [i, s])
        (fi, fs) = _run(
            main, startup,
            {"i": np.zeros(1, np.float32), "s": np.zeros(1, np.float32),
             "limit": np.full(1, 5.0, np.float32)},
            [iv, sv])
        assert float(fi[0]) == 5.0
        assert float(fs[0]) == 0 + 1 + 2 + 3 + 4
    finally:
        paddle.disable_static()


def test_switch_case_and_default():
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            idx = static.data(name="idx", shape=[1], dtype="int32")
            x = static.data(name="x", shape=[3], dtype="float32")
            out = static.nn.switch_case(
                idx,
                [lambda: x + 10.0, lambda: x * 2.0],
                default=lambda: x * 0.0)
        xv = np.arange(3, dtype=np.float32)
        for i, want in [(0, xv + 10), (1, xv * 2), (7, xv * 0)]:
            (got,) = _run(main, startup,
                          {"idx": np.full(1, i, np.int32), "x": xv}, [out])
            np.testing.assert_allclose(got, want)
    finally:
        paddle.disable_static()


def test_case_first_true_wins():
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            a = static.data(name="a", shape=[1], dtype="float32")
            x = static.data(name="x", shape=[2], dtype="float32")
            out = static.case(
                [(a > 2.0, lambda: x + 100.0), (a > 0.0, lambda: x + 1.0)],
                default=lambda: x - 1.0)
        xv = np.zeros(2, np.float32)
        for av, want in [(5.0, xv + 100), (1.0, xv + 1), (-3.0, xv - 1)]:
            (got,) = _run(main, startup,
                          {"a": np.full(1, av, np.float32), "x": xv}, [out])
            np.testing.assert_allclose(got, want)
    finally:
        paddle.disable_static()


def test_cond_backward():
    """append_backward differentiates through lax.cond."""
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data(name="x", shape=[3], dtype="float32")
            x.stop_gradient = False
            p = static.data(name="p", shape=[1], dtype="float32")
            y = static.nn.cond(p, lambda: x * 3.0, lambda: x * 5.0)
            loss = paddle.static.nn.reduce_sum(y) if hasattr(
                paddle.static.nn, "reduce_sum") else None
            if loss is None:
                from paddle_tpu.static.nn_static import reduce_sum

                loss = reduce_sum(y)
            grads = static.gradients([loss], [x])
        xv = np.ones(3, np.float32)
        (g,) = _run(main, startup,
                    {"x": xv, "p": np.ones(1, np.float32)}, [grads[0]])
        np.testing.assert_allclose(g, np.full(3, 3.0))
        (g2,) = _run(main, startup,
                     {"x": xv, "p": np.zeros(1, np.float32)}, [grads[0]])
        np.testing.assert_allclose(g2, np.full(3, 5.0))
    finally:
        paddle.disable_static()


def test_switch_case_no_default_dispatches_max_key():
    """ADVICE r1 (medium): unmatched index with no default must run the
    max-key branch (control_flow.py:3592), not branch position 0; and the
    dict branch_fns form must be accepted."""
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            idx = static.data(name="idx", shape=[1], dtype="int32")
            x = static.data(name="x", shape=[3], dtype="float32")
            out = static.nn.switch_case(
                idx, {3: lambda: x + 10.0, 1: lambda: x * 2.0})
        xv = np.arange(3, dtype=np.float32)
        for i, want in [(1, xv * 2), (3, xv + 10), (99, xv + 10)]:
            (got,) = _run(main, startup,
                          {"idx": np.full(1, i, np.int32), "x": xv}, [out])
            np.testing.assert_allclose(got, want)
    finally:
        paddle.disable_static()
