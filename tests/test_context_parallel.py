"""Sequence/context parallelism: ring + Ulysses attention vs dense oracle.

The reference has no long-context support (SURVEY §5.7); these tests hold the
TPU-native extension to the same dist-test contract as everything else —
sharded results must match the single-device computation numerically,
including gradients (ppermute/all_to_all transposes under vjp).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.parallel.collective import shard_map
from paddle_tpu.parallel.context_parallel import (
    _ring_attention_raw, _ulysses_attention_raw,
)
from paddle_tpu.parallel.env import build_mesh
from paddle_tpu.parallel.hybrid import CompiledTrainStep
from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny


def _dense_attention(q, k, v, causal):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q * scale, k)
    if causal:
        L = s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((L, L), bool)), s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)


def _qkv(seed=0, B=2, H=4, L=32, D=8):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, H, L, D).astype(np.float32))
    return mk(), mk(), mk()


SEQ_SPEC = P(None, None, "seq", None)


def _sharded(fn_raw, mesh, causal, **kw):
    def f(q, k, v):
        return fn_raw(q, k, v, "seq", causal, **kw)

    return shard_map(f, mesh, (SEQ_SPEC,) * 3, SEQ_SPEC)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    q, k, v = _qkv()
    mesh = build_mesh({"seq": 4})
    out = _sharded(_ring_attention_raw, mesh, causal)(q, k, v)
    ref = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_dense(causal):
    q, k, v = _qkv()
    mesh = build_mesh({"seq": 4})
    out = _sharded(_ulysses_attention_raw, mesh, causal, use_flash=False)(
        q, k, v)
    ref = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("raw,kw", [
    (_ring_attention_raw, {}),
    (_ulysses_attention_raw, {"use_flash": False}),
])
def test_context_parallel_grads_match_dense(raw, kw):
    q, k, v = _qkv(seed=1)
    mesh = build_mesh({"seq": 4})
    sharded = _sharded(raw, mesh, True, **kw)
    # weighted sum so the cotangent is non-uniform
    w = jnp.asarray(np.random.RandomState(2).randn(*q.shape)
                    .astype(np.float32))

    g_sh = jax.grad(lambda *a: jnp.sum(sharded(*a) * w), argnums=(0, 1, 2))(
        q, k, v)
    g_ref = jax.grad(
        lambda *a: jnp.sum(_dense_attention(*a, True) * w), argnums=(0, 1, 2)
    )(q, k, v)
    for a, b in zip(g_sh, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def _gpt_losses(mesh_dims, cp_mode, n_steps=2, seed=0):
    paddle.seed(seed)
    cfg = gpt_tiny()
    cfg.dropout = 0.0
    cfg.cp_mode = cp_mode
    model = GPTForPretraining(cfg)
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (4, 32)).astype(np.int32)
    labels = rng.randint(0, cfg.vocab_size, (4, 32)).astype(np.int32)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    mesh = build_mesh(mesh_dims)
    tr = CompiledTrainStep(model, lambda m, i, l: m.loss(i, l), opt, mesh,
                           zero_shard_states=False)
    return [
        float(np.asarray(tr.step(paddle.to_tensor(ids),
                                 paddle.to_tensor(labels))._data))
        for _ in range(n_steps)
    ]


@pytest.mark.parametrize("cp_mode", ["ring", "ulysses"])
def test_gpt_seq_parallel_training_matches_dp(cp_mode):
    ref = _gpt_losses({"data": 2}, cp_mode="ring")  # no seq axis -> dense
    cp = _gpt_losses({"data": 2, "seq": 4}, cp_mode=cp_mode)
    np.testing.assert_allclose(cp, ref, rtol=2e-4, atol=2e-4)
