"""Suite-size ratchet: the test count may only grow.

Motivation (round 3): a bad patch once corrupted a test module in a way
that silently DELETED two tests while everything still imported — the
suite stayed green because the assertions were simply gone.  This
ratchet makes that class of loss loud: if `def test_` count drops below
the committed floor, someone deleted coverage without saying so.
Raise the floor when adding tests (never lower it silently).
"""
import pathlib
import re

FLOOR = 949  # committed minimum number of test FUNCTIONS under
# tests/ (parametrize expansion makes the collected count higher)


def test_suite_size_only_grows():
    here = pathlib.Path(__file__).parent
    count = 0
    for p in here.glob("*.py"):
        count += len(re.findall(r"^def test_", p.read_text(), re.M))
        count += len(re.findall(r"^    def test_", p.read_text(), re.M))
    assert count >= FLOOR, (
        f"test function count {count} fell below the committed floor "
        f"{FLOOR}: tests were deleted (or a module was corrupted) — "
        "restore them or consciously lower the floor with a rationale")
