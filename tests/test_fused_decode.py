"""Fused single-dispatch decode step + decode-batch bucketing.

Acceptance oracles (all CPU, fused path FORCED — on CPU the engine
defaults to the eager-exact path, which is what keeps the zero-tolerance
token-identity oracle anchored):

1. With decode="fused", a decode step performs exactly ONE jitted
   dispatch and at most ONE host sync — asserted via the instrumented
   generation.decode_dispatches_per_step / decode_host_syncs_per_step
   gauges, not estimated.
2. Fused greedy decode is token-identical to the eager sequential
   full-recompute oracle across varying live batch sizes — joins,
   finishes, forced preemption.
3. Dummy padding rows (the batch bucket's unfilled tail) NEVER write a
   pool page: their scatter is routed to the out-of-range sentinel and
   dropped on device.
4. The decode bucket cache compiles at most one executable per
   (batch bucket, pages bucket, greedy) signature — repeat traffic adds
   zero compiles.

Plus the kernel-layout pool satellite (pool_layout="kernel": scatters
write [H, P, page_size, D] so the Pallas kernel skips its per-call
whole-pool transpose; the jnp reference gather is re-proven BITWISE) and
the vectorized host-sampling satellite (one argmax for all greedy rows;
stochastic rows keep their per-request RNGs).
"""
import numpy as np
import pytest

from paddle_tpu import generation as gen
from paddle_tpu.generation import metrics as gmetrics
from paddle_tpu.profiler.monitor import StatRegistry


@pytest.fixture(autouse=True)
def _fresh_generation_stats():
    reg = StatRegistry.instance()
    for name in list(reg.stats()):
        if name.startswith(gmetrics.PREFIX):
            reg.get_stat(name).reset()
    yield


@pytest.fixture(scope="module")
def model():
    return gen.TinyCausalLM(vocab_size=48, num_layers=2, num_heads=2,
                            head_dim=8, seed=3)


from gen_oracle import greedy_oracle as _ref  # noqa: E402  cross-module memo


def _engine(model, *, slots=4, pages=64, page_size=4, decode="fused",
            start=False, **kw):
    cfg = gen.GenerationConfig(max_decode_slots=slots, num_pages=pages,
                               page_size=page_size, kv_backend="device",
                               decode=decode, **kw)
    return gen.GenerationEngine(model, cfg, start=start)


PROMPTS = [[1, 2, 3], [7, 5], [9, 9, 9, 4, 2], [11]]


# ------------------- acceptance: one dispatch, one sync -------------------


def test_fused_step_is_one_dispatch_one_sync(model):
    """Acceptance oracle 1: pure-decode steps on the fused path set the
    instrumented gauges to exactly (1, 1); the eager path on the same
    workload issues 2 device calls per layer."""
    eng = _engine(model)
    for p in PROMPTS:
        eng.submit(p, max_new_tokens=8)
    eng.step()  # admit + prefill + first decode
    stats = eng.metrics.snapshot()
    for _ in range(3):
        eng.step()  # pure decode steps
        stats = eng.metrics.snapshot()
        assert stats["generation.decode_dispatches_per_step"] == 1
        assert stats["generation.decode_host_syncs_per_step"] <= 1
    eng.run_until_idle()
    eng.shutdown()

    eager = _engine(model, decode="eager")
    for p in PROMPTS:
        eager.submit(p, max_new_tokens=4)
    eager.step()
    eager.step()
    stats = eager.metrics.snapshot()
    # eager device backend: one scatter + one attention per layer
    assert stats["generation.decode_dispatches_per_step"] == \
        2 * model.num_layers
    assert stats["generation.decode_host_syncs_per_step"] == 1
    eager.run_until_idle()
    eager.shutdown()


def test_fused_all_greedy_uses_device_argmax_variant(model):
    """An all-greedy batch compiles (only) the greedy executable — the
    step's host fetch is [B] token ids, not [B, V] logits."""
    eng = _engine(model)
    handles = [eng.submit(p, max_new_tokens=6) for p in PROMPTS]
    eng.run_until_idle()
    for h in handles:
        h.result(timeout=5)
    assert eng._fused._exec[True].compile_count >= 1
    assert eng._fused._exec[False].compile_count == 0
    eng.shutdown()


# --------------------- token identity vs the oracle ----------------------


def test_fused_greedy_token_identical_to_oracle(model):
    eng = _engine(model)
    handles = [eng.submit(p, max_new_tokens=12) for p in PROMPTS]
    eng.run_until_idle()
    for h, p in zip(handles, PROMPTS):
        assert h.result(timeout=5).token_ids == \
            _ref(model, p, 12)
    assert eng.cache.utilization() == 0.0
    assert eng.cache.num_free_pages == eng.cache.num_pages
    eng.shutdown()


def test_fused_token_identical_under_forced_preemption(model):
    """Acceptance oracle 2 (preemption): a pool sized to thrash forces
    recompute preemption mid-fused-decode; victims re-prefill and every
    token still matches."""
    eng = _engine(model, pages=9)
    handles = [eng.submit(p, max_new_tokens=12) for p in PROMPTS]
    eng.run_until_idle()
    results = [h.result(timeout=5) for h in handles]
    for res, p in zip(results, PROMPTS):
        assert res.token_ids == _ref(model, p, 12)
    assert sum(r.preemptions for r in results) > 0
    assert eng.cache.utilization() == 0.0
    eng.shutdown()


def test_fused_token_identity_across_joins_and_finishes(model):
    """Acceptance oracle 2 (ragged batches): sequences join mid-stream
    and finish at different steps, so the live batch size B (and with it
    the padded batch bucket) changes across the run."""
    eng = _engine(model)
    h1 = eng.submit([1, 2, 3], max_new_tokens=15)
    h2 = eng.submit([7, 5], max_new_tokens=3)       # finishes early
    for _ in range(5):
        eng.step()
    h3 = eng.submit([9, 9, 9, 4, 2], max_new_tokens=8)  # joins mid-stream
    h4 = eng.submit([11], max_new_tokens=1)
    eng.run_until_idle()
    for h, p, n in ((h1, [1, 2, 3], 15), (h2, [7, 5], 3),
                    (h3, [9, 9, 9, 4, 2], 8), (h4, [11], 1)):
        assert h.result(timeout=5).token_ids == _ref(model, p, n)
    assert eng.cache.utilization() == 0.0
    eng.shutdown()


def test_fused_background_worker_end_to_end(model):
    eng = _engine(model, start=True)
    try:
        h = eng.submit([5, 6, 7], max_new_tokens=8)
        assert list(h.tokens(timeout=30)) == \
            _ref(model, [5, 6, 7], 8)
    finally:
        eng.shutdown()


def test_failed_fused_dispatch_resets_pools_engine_keeps_serving(model):
    """A dispatch that dies AFTER consuming its donated pool buffers
    must not zombie the engine: the cache is reset to fresh storage, the
    poisoned step fails its batch (engine._worker contract), and later
    requests decode correctly on the zeroed pools."""
    eng = _engine(model, start=True)
    try:
        fused = eng._fused
        num_layers = fused._num_layers

        class _DyingExec:
            def __init__(self, inner):
                self._inner = inner

            def get(self, args):
                self._inner.get(args)  # real compile path

                def boom(*a):
                    for pool in a[4:4 + 2 * num_layers]:
                        pool.delete()  # donation consumed the buffers
                    raise RuntimeError("device fell over mid-dispatch")
                return boom

        real = dict(fused._exec)
        fused._exec = {k: _DyingExec(v) for k, v in real.items()}
        h = eng.submit([1, 2, 3], max_new_tokens=4)
        with pytest.raises(RuntimeError, match="mid-dispatch"):
            h.result(timeout=30)
        fused._exec = real

        h2 = eng.submit([1, 2, 3], max_new_tokens=6)
        assert list(h2.tokens(timeout=30)) == _ref(model, [1, 2, 3], 6)
    finally:
        eng.shutdown()


def test_fused_bf16_pool_matches_eager_device(model):
    """Low-precision pools through the fused path: the in-trace scatter
    casts at storage exactly like the eager scatter, so fused bf16
    tokens equal eager-device bf16 tokens."""
    import jax.numpy as jnp

    toks = {}
    for decode in ("eager", "fused"):
        eng = _engine(model, decode=decode, kv_dtype=jnp.bfloat16)
        handles = [eng.submit(p, max_new_tokens=8) for p in PROMPTS]
        eng.run_until_idle()
        toks[decode] = [h.result(timeout=5).token_ids for h in handles]
        eng.shutdown()
    assert toks["fused"] == toks["eager"]


# ------------------------ dummy padding rows -----------------------------


def test_fused_dummy_rows_never_write_a_pool_page(model):
    """Acceptance oracle 3: with 3 live sequences padded to the 4-batch
    bucket, every step carries one dummy row whose position would alias
    page 0 row 0 — mid-flight, every page outside the live page tables
    must still be exactly zero."""
    eng = _engine(model, slots=4, pages=16)
    handles = [eng.submit(p, max_new_tokens=8) for p in PROMPTS[:3]]
    eng.step()            # prefill + first sample
    for _ in range(3):
        eng.step()        # fused decode with a dummy 4th row
        owned = set()
        for s in eng.scheduler.active():
            owned |= set(eng.cache.page_table(s.seq_id))
        pool_k, pool_v = eng.cache.k_pool, eng.cache.v_pool
        for page in range(eng.cache.num_pages):
            if page not in owned:
                np.testing.assert_array_equal(pool_k[:, page], 0.0)
                np.testing.assert_array_equal(pool_v[:, page], 0.0)
    eng.run_until_idle()
    for h, p in zip(handles, PROMPTS[:3]):
        assert h.result(timeout=5).token_ids == _ref(model, p, 8)
    eng.shutdown()


# ----------------------- bucket cache compile bounds ----------------------


def test_fused_compile_count_bounded_by_bucket_menu(model):
    """Acceptance oracle 4: repeat traffic through seen (batch, pages)
    buckets never compiles again; the count equals the distinct cached
    signatures and lands in generation.decode_compiles_total."""
    eng = _engine(model, slots=4, pages=64)

    def burst():
        handles = [eng.submit(p, max_new_tokens=6) for p in PROMPTS]
        eng.run_until_idle()
        for h in handles:
            h.result(timeout=5)

    burst()
    first = eng._fused.compile_count
    assert first >= 1
    burst()                      # identical shapes: all cache hits
    assert eng._fused.compile_count == first
    stats = eng.metrics.snapshot()
    assert stats["generation.decode_compiles_total"] == first
    assert stats["generation.decode_cache_hits"] > 0
    cached = sum(len(v) for v in eng._fused.cached_buckets().values())
    assert cached == first
    eng.shutdown()


def test_fused_requires_device_backend_and_protocol(model):
    with pytest.raises(ValueError, match="fused"):
        gen.GenerationEngine(model, gen.GenerationConfig(
            kv_backend="host", decode="fused"), start=False)

    class NoFuse:
        num_layers, num_heads, head_dim, vocab_size = 1, 1, 4, 8

        def prefill(self, tokens):
            raise NotImplementedError

        def decode(self, tokens, positions, attend):
            raise NotImplementedError

    with pytest.raises(ValueError, match="decode_step_fn"):
        gen.GenerationEngine(NoFuse(), gen.GenerationConfig(
            kv_backend="device", decode="fused"), start=False)
    with pytest.raises(ValueError):
        gen.GenerationConfig(decode="warp")


# ------------------------- kernel-layout pools ----------------------------


def test_kernel_layout_pool_is_dropin_bitwise():
    """Same op sequence -> bitwise-identical canonical pool contents in
    both layouts, across every write path."""
    rng = np.random.default_rng(0)
    tok = gen.DeviceKVPool(2, 2, 8, num_pages=8, page_size=4)
    ker = gen.DeviceKVPool(2, 2, 8, num_pages=8, page_size=4,
                           pool_layout="kernel")
    k = rng.standard_normal((2, 6, 2, 8)).astype(np.float32)
    step = rng.standard_normal((2, 2, 8)).astype(np.float32)
    for c in (tok, ker):
        c.allocate("s")
        c.allocate("t")
        c.append_prefill("s", k, -k)
        c.append("t", k[:, 0], -k[:, 0])
        c.reserve("s", 1)
        c.reserve("t", 1)
        c.write_decode_tokens(["s", "t"], [6, 1], 0, step, -step)
    np.testing.assert_array_equal(tok.k_pool, ker.k_pool)
    np.testing.assert_array_equal(tok.v_pool, ker.v_pool)
    # raw storage really is the kernel layout: [H, P, page_size, D]
    kp, _ = ker.layer_pools(0)
    assert kp.shape == (2, 8, 4, 8)


def test_kernel_layout_reference_gather_bitwise():
    """The satellite's re-proof: the jnp reference over kernel-layout
    pools is BITWISE equal to the token-layout reference (the gather
    permutation is value-preserving and the einsums see identical
    operands)."""
    tok = gen.DeviceKVPool(1, 2, 8, num_pages=16, page_size=4)
    ker = gen.DeviceKVPool(1, 2, 8, num_pages=16, page_size=4,
                           pool_layout="kernel")
    rng = np.random.default_rng(2)
    spans = [rng.standard_normal((1, t, 2, 8)).astype(np.float32)
             for t in (13, 5, 24)]
    for c in (tok, ker):
        for i, kv in enumerate(spans):
            c.allocate(i)
            c.append_prefill(i, kv, -kv)
    q = np.random.default_rng(3).standard_normal((3, 2, 8)) \
        .astype(np.float32)
    pt, sl = tok.gather_block_tables([0, 1, 2])
    ref_tok = np.asarray(gen.paged_decode_attention_reference(
        q, *tok.layer_pools(0), pt, sl))
    ref_ker = np.asarray(gen.paged_decode_attention_reference(
        q, *ker.layer_pools(0), pt, sl, layout="kernel"))
    np.testing.assert_array_equal(ref_tok, ref_ker)


def test_kernel_layout_pallas_interpret_matches_reference():
    """The Pallas kernel consumes kernel-layout pools as stored (no
    transpose) and still matches the reference semantics."""
    rng = np.random.default_rng(4)
    ker = gen.DeviceKVPool(1, 2, 128, num_pages=16, page_size=8,
                           pool_layout="kernel")
    for i, t in enumerate((13, 5, 24)):
        kv = rng.standard_normal((1, t, 2, 128)).astype(np.float32)
        ker.allocate(i)
        ker.append_prefill(i, kv, -kv)
    q = rng.standard_normal((3, 2, 128)).astype(np.float32)
    pt, sl = ker.gather_block_tables([0, 1, 2])
    kp, vp = ker.layer_pools(0)
    ref = np.asarray(gen.paged_decode_attention_reference(
        q, kp, vp, pt, sl, layout="kernel"))
    out = np.asarray(gen.paged_decode_attention(
        q, kp, vp, pt, sl, use_kernel=True, interpret=True,
        layout="kernel"))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("decode", ["eager", "fused"])
def test_kernel_layout_engine_token_identical(model, decode):
    """End to end on the kernel layout, both decode paths: tokens match
    the oracle, including under forced preemption."""
    eng = _engine(model, pages=9, decode=decode, pool_layout="kernel")
    handles = [eng.submit(p, max_new_tokens=10) for p in PROMPTS]
    eng.run_until_idle()
    results = [h.result(timeout=5) for h in handles]
    for res, p in zip(results, PROMPTS):
        assert res.token_ids == _ref(model, p, 10)
    assert sum(r.preemptions for r in results) > 0
    assert eng.cache.utilization() == 0.0
    eng.shutdown()


def test_kernel_layout_rejected_on_host_backend(model):
    with pytest.raises(ValueError, match="kernel"):
        gen.GenerationEngine(model, gen.GenerationConfig(
            kv_backend="host", pool_layout="kernel"), start=False)
    with pytest.raises(ValueError):
        gen.DeviceKVPool(1, 1, 4, pool_layout="sideways")


# ---------------------- vectorized host sampling --------------------------


def test_sample_tokens_batch_matches_per_row():
    """The vectorized sampler is row-for-row identical to sample_token:
    greedy rows share one argmax, stochastic rows replay their RNGs."""
    rng = np.random.default_rng(8)
    logits = rng.standard_normal((6, 32)).astype(np.float32)
    params = [gen.SamplingParams(),                       # greedy
              gen.SamplingParams(temperature=1.1, seed=1),
              gen.SamplingParams(),                       # greedy
              gen.SamplingParams(temperature=0.7, top_k=5, seed=2),
              gen.SamplingParams(temperature=1.3, top_p=0.9, seed=3),
              gen.SamplingParams()]                       # greedy
    batch = gen.sample_tokens_batch(
        logits, params, [p.make_rng() for p in params])
    single = [gen.sample_token(logits[i], p, p.make_rng())
              for i, p in enumerate(params)]
    assert batch == single


def test_eager_mixed_batch_sampling_regression(model):
    """Regression for the engine's vectorized decode sampling: a mixed
    greedy/stochastic batch reproduces the same streams as the same
    requests served alone (per-request RNG independence survives the
    batch argmax split)."""
    stoch = dict(max_new_tokens=10,
                 sampling=gen.SamplingParams(temperature=0.9, top_k=10,
                                             seed=42))

    def run(prompts_with_kw):
        eng = _engine(model, decode="eager")
        handles = [eng.submit(p, **kw) for p, kw in prompts_with_kw]
        eng.run_until_idle()
        out = [h.result(timeout=5).token_ids for h in handles]
        eng.shutdown()
        return out

    together = run([([1, 2, 3], dict(max_new_tokens=10)),
                    ([7, 5], dict(stoch)),
                    ([9, 4], dict(max_new_tokens=10))])
    alone = [run([([1, 2, 3], dict(max_new_tokens=10))])[0],
             run([([7, 5], dict(stoch))])[0],
             run([([9, 4], dict(max_new_tokens=10))])[0]]
    assert together == alone
    assert together[0] == _ref(model, [1, 2, 3], 10)


def test_fused_mixed_batch_matches_eager(model):
    """A mixed batch forces the fused logits variant (host sampling);
    tokens match the eager path seed for seed."""
    def run(decode):
        eng = _engine(model, decode=decode)
        hs = [eng.submit([1, 2, 3], max_new_tokens=10),
              eng.submit([7, 5], max_new_tokens=10,
                         sampling=gen.SamplingParams(temperature=0.9,
                                                     top_k=10, seed=42)),
              eng.submit([9, 4], max_new_tokens=10,
                         sampling=gen.SamplingParams(temperature=1.2,
                                                     top_p=0.9, seed=7))]
        eng.run_until_idle()
        out = [h.result(timeout=5).token_ids for h in hs]
        eng.shutdown()
        return out

    assert run("fused") == run("eager")


# ------------------------- kv bytes on the fused path ---------------------


def test_fused_kv_bytes_stay_o_tokens(model):
    """The fused scatter happens inside the dispatch, but the counted
    write bound stays O(batch x layers x heads x head_dim) per step,
    independent of pool size — comparable with the eager A/B."""
    def steady_deltas(pages):
        eng = _engine(model, slots=4, pages=pages)
        for p in PROMPTS:
            eng.submit(p, max_new_tokens=10)
        stat = eng.metrics._stat(gmetrics.KV_BYTES_MOVED)
        eng.step()
        deltas = []
        for _ in range(4):
            before = stat.get()
            assert eng.step() == 4
            deltas.append(stat.get() - before)
        eng.run_until_idle()
        eng.shutdown()
        return deltas

    small, big = steady_deltas(32), steady_deltas(256)
    assert small == big
    payload = 2 * 4 * model.num_layers * model.num_heads * model.head_dim * 4
    for delta in small:
        assert 0 < delta <= payload
