"""One executed golden case per served _C_ops name (VERDICT r2 missing #5).

Ratchets tests/test_c_ops_surface.py from name-resolution to execution:
EVERY non-absent alias in paddle_tpu._C_ops runs at least once here —
eager with a numpy oracle (or a property check where the op is random /
data-dependent), a static emit+Executor leg for deterministic pure ops,
and central-finite-difference grad checks on the differentiable core.
The closing test asserts executed == served, so a new alias without a
case fails CI.  Ref: op_test.py:270,1078,1409.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import _C_ops


def _r(seed=0):
    return np.random.RandomState(seed)


def F(shape, seed=0, lo=-1.0, hi=1.0):
    return (_r(seed).uniform(lo, hi, shape)).astype(np.float32)


def I(shape, hi, seed=0, dtype=np.int64):
    return _r(seed).randint(0, hi, shape).astype(dtype)


class C:
    """One case: args (np.ndarray entries become Tensors; lists of arrays
    become lists of Tensors; everything else passes through), kwargs,
    and exactly one of ref (numpy oracle) / check (property assert)."""

    def __init__(self, make, ref=None, check=None, grad=(), static=None,
                 kwargs=None, rtol=1e-4, atol=1e-5):
        self.make = make
        self.ref = ref
        self.check = check
        self.grad = tuple(grad)
        self.kwargs = kwargs or {}
        # static leg defaults on only for deterministic array->array ops
        self.static = (ref is not None) if static is None else static
        self.rtol = rtol
        self.atol = atol


def _to_tensor_args(args):
    out = []
    tensor_idx = []
    for i, a in enumerate(args):
        if isinstance(a, np.ndarray):
            out.append(paddle.to_tensor(a))
            tensor_idx.append(i)
        elif isinstance(a, (list, tuple)) and a and all(
                isinstance(x, np.ndarray) for x in a):
            out.append([paddle.to_tensor(x) for x in a])
        else:
            out.append(a)
    return out, tensor_idx


def _leaves(out):
    from paddle_tpu.core.tensor import Tensor

    if isinstance(out, Tensor):
        return [np.asarray(out._data)]
    if isinstance(out, (list, tuple)):
        res = []
        for o in out:
            res.extend(_leaves(o))
        return res
    if out is None:
        return []
    return [np.asarray(out)]


def _run_eager(name, c):
    fn = getattr(_C_ops, name)
    args = c.make()
    targs, _ = _to_tensor_args(args)
    paddle.seed(1234)
    out = fn(*targs, **c.kwargs)
    got = _leaves(out)
    if c.ref is not None:
        refs = c.ref(*args)
        refs = refs if isinstance(refs, (list, tuple)) else [refs]
        assert len(got) >= len(refs), (name, len(got), len(refs))
        for g, r in zip(got, refs):
            np.testing.assert_allclose(
                np.asarray(g, np.float64), np.asarray(r, np.float64),
                rtol=c.rtol, atol=c.atol, err_msg=f"{name}: eager mismatch")
    if c.check is not None:
        res = c.check(got, args)
        # boolean-lambda property checks must actually gate the test
        assert res is None or res, f"{name}: property check failed"
    return args, got


def _run_static(name, c, args, expected):
    import paddle_tpu.static as static
    from paddle_tpu.static.nn_static import emit
    from paddle_tpu.core import autograd
    from paddle_tpu.core.tensor import _wrap_data

    fn = getattr(_C_ops, name)
    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, np.ndarray)]
    if not tensor_idx or any(a.ndim == 0 for a in args
                             if isinstance(a, np.ndarray)):
        return
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            feed_vars = [
                static.data(f"x{i}", list(args[i].shape),
                            dtype=str(args[i].dtype))
                for i in tensor_idx
            ]

            def body(*vals):
                full = list(args)
                for i, v in zip(tensor_idx, vals):
                    full[i] = _wrap_data(v)
                with autograd.no_grad():
                    out = fn(*full, **c.kwargs)
                leaves = _leaves_traced(out)
                return tuple(leaves) if len(leaves) != 1 else leaves[0]

            outs_spec = [(f"O{i}", list(e.shape), str(e.dtype))
                         for i, e in enumerate(expected)]
            out_vars = emit(f"case_{name}",
                            [(f"X{i}", v) for i, v in enumerate(feed_vars)],
                            outs_spec, body)
            if not isinstance(out_vars, list):
                out_vars = [out_vars]
        exe = static.Executor()
        exe.run(startup)
        res = exe.run(main, feed={f"x{i}": args[i] for i in tensor_idx},
                      fetch_list=out_vars)
        for g, e in zip(res, expected):
            np.testing.assert_allclose(
                np.asarray(g, np.float64), np.asarray(e, np.float64),
                rtol=max(c.rtol, 1e-4), atol=max(c.atol, 1e-5),
                err_msg=f"{name}: static leg mismatch")
    finally:
        paddle.disable_static()


def _leaves_traced(out):
    from paddle_tpu.core.tensor import Tensor

    if isinstance(out, Tensor):
        return [out._data]
    if isinstance(out, (list, tuple)):
        res = []
        for o in out:
            res.extend(_leaves_traced(o))
        return res
    return [out] if out is not None else []


def _run_grad(name, c, args):
    fn = getattr(_C_ops, name)
    for idx in c.grad:
        targs, _ = _to_tensor_args(args)
        for j, t in enumerate(targs):
            if hasattr(t, "stop_gradient"):
                t.stop_gradient = (j != idx)
        paddle.seed(1234)
        out = fn(*targs, **c.kwargs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        loss = None
        for o in outs:
            if hasattr(o, "_data") and np.issubdtype(
                    np.asarray(o._data).dtype, np.floating):
                term = o.sum()
                loss = term if loss is None else loss + term
        assert loss is not None, f"{name}: nothing differentiable"
        loss.backward()
        analytic = np.asarray(targs[idx].grad._data, np.float64)

        def scalar(x_np):
            t2, _ = _to_tensor_args(
                [x_np if i == idx else a for i, a in enumerate(args)])
            paddle.seed(1234)
            o2 = fn(*t2, **c.kwargs)
            o2 = o2 if isinstance(o2, (list, tuple)) else [o2]
            tot = 0.0
            for o in o2:
                if hasattr(o, "_data") and np.issubdtype(
                        np.asarray(o._data).dtype, np.floating):
                    tot += float(np.sum(np.asarray(o._data, np.float64)))
            return tot

        x = args[idx].astype(np.float64)
        num = np.zeros_like(x)
        xf, nf = x.reshape(-1), num.reshape(-1)
        d = 1e-3
        for i in range(xf.size):
            orig = xf[i]
            xf[i] = orig + d
            hi = scalar(x.astype(np.float32))
            xf[i] = orig - d
            lo = scalar(x.astype(np.float32))
            xf[i] = orig
            nf[i] = (hi - lo) / (2 * d)
        np.testing.assert_allclose(
            analytic, num, rtol=1e-2, atol=1e-2,
            err_msg=f"{name}: grad mismatch wrt arg {idx}")


# ---------------------------------------------------------------------------
# case helpers


def unary(np_fn, lo=-0.9, hi=0.9, shape=(2, 3), grad=True, **kw):
    return C(lambda: [F(shape, 7, lo, hi)],
             ref=lambda a: np_fn(a.astype(np.float64)),
             grad=(0,) if grad else (), **kw)


def binary(np_fn, lo=-1.0, hi=1.0, grad=(0, 1), **kw):
    return C(lambda: [F((2, 3), 1, lo, hi), F((2, 3), 2, lo, hi)],
             ref=lambda a, b: np_fn(a.astype(np.float64),
                                    b.astype(np.float64)),
             grad=grad, **kw)


def compare(np_fn):
    return C(lambda: [F((2, 3), 1), F((2, 3), 2)],
             ref=lambda a, b: np_fn(a, b).astype(np.float64), atol=0)


def bitwise(np_fn, n=2):
    return C(lambda: [I((2, 3), 8, 1, np.int32)][:n] + (
        [I((2, 3), 8, 2, np.int32)] if n == 2 else []),
             ref=(lambda a, b: np_fn(a, b)) if n == 2 else (lambda a: np_fn(a)),
             atol=0)


def logical(np_fn, n=2):
    mk = lambda: ([(F((2, 3), 1) > 0), (F((2, 3), 2) > 0)][:n])
    return C(lambda: [a.astype(bool) for a in mk()],
             ref=(lambda a, b: np_fn(a, b)) if n == 2 else (lambda a: np_fn(a)),
             atol=0)


def prop(make, check, **kw):
    return C(make, check=check, static=False, **kw)


def finite(make, min_outputs=1, **kw):
    def chk(got, args):
        assert len(got) >= min_outputs
        for g in got:
            if np.issubdtype(g.dtype, np.floating):
                assert np.isfinite(g).all()
    return C(make, check=chk, static=False, **kw)


def shape_is(make, shape, **kw):
    return C(make, check=lambda got, args: got[0].shape == tuple(shape),
             static=False, **kw)


_SM = lambda a: np.exp(a) / np.exp(a).sum(-1, keepdims=True)


def _np_softmax(a, axis=-1):
    a = a - a.max(axis=axis, keepdims=True)
    e = np.exp(a)
    return e / e.sum(axis=axis, keepdims=True)


def _psd(n=3, seed=3):
    a = _r(seed).rand(n, n)
    return (a @ a.T + n * np.eye(n)).astype(np.float32)


# ---------------------------------------------------------------------------
# the full case table — one entry per served alias name

CASES = {}

# --- elementwise / math unaries
CASES["abs"] = unary(np.abs)
CASES["acos"] = unary(np.arccos)
CASES["acosh"] = unary(np.arccosh, lo=1.1, hi=3.0)
CASES["asin"] = unary(np.arcsin)
CASES["asinh"] = unary(np.arcsinh)
CASES["atan"] = unary(np.arctan)
CASES["atanh"] = unary(np.arctanh)
CASES["ceil"] = unary(np.ceil, grad=False, atol=0)
CASES["cos"] = unary(np.cos)
CASES["cosh"] = unary(np.cosh)
CASES["digamma"] = unary(lambda a: _scipy_digamma(a), lo=0.5, hi=3.0)
CASES["erf"] = unary(lambda a: _scipy_erf(a))
CASES["exp"] = unary(np.exp)
CASES["expm1"] = unary(np.expm1)
CASES["floor"] = unary(np.floor, grad=False, atol=0)
CASES["lgamma"] = unary(lambda a: _scipy_gammaln(a), lo=0.5, hi=3.0)
CASES["log"] = unary(np.log, lo=0.1, hi=2.0)
CASES["log10"] = unary(np.log10, lo=0.1, hi=2.0)
CASES["log1p"] = unary(np.log1p, lo=-0.5, hi=2.0)
CASES["log2"] = unary(np.log2, lo=0.1, hi=2.0)
CASES["reciprocal"] = unary(np.reciprocal, lo=0.2, hi=2.0)
CASES["rsqrt"] = unary(lambda a: 1.0 / np.sqrt(a), lo=0.2, hi=2.0)
CASES["sigmoid"] = unary(lambda a: 1 / (1 + np.exp(-a)))
CASES["sign"] = unary(np.sign, grad=False, atol=0)
CASES["sin"] = unary(np.sin)
CASES["sinh"] = unary(np.sinh)
CASES["sqrt"] = unary(np.sqrt, lo=0.2, hi=2.0)
CASES["square"] = unary(np.square)
CASES["tan"] = unary(np.tan)
CASES["tanh"] = unary(np.tanh)
CASES["trunc"] = unary(np.trunc, grad=False, atol=0)
CASES["conj"] = unary(np.conj, grad=False)
CASES["real"] = C(lambda: [(F((2, 2), 1) + 1j * F((2, 2), 2)).astype(
    np.complex64)], ref=lambda a: np.real(a))
CASES["imag"] = C(lambda: [(F((2, 2), 1) + 1j * F((2, 2), 2)).astype(
    np.complex64)], ref=lambda a: np.imag(a))
CASES["isfinite_v2"] = C(
    lambda: [np.array([1.0, np.inf, np.nan], np.float32)],
    ref=lambda a: np.isfinite(a), atol=0)
CASES["isinf_v2"] = C(
    lambda: [np.array([1.0, np.inf, np.nan], np.float32)],
    ref=lambda a: np.isinf(a), atol=0)
CASES["isnan_v2"] = C(
    lambda: [np.array([1.0, np.inf, np.nan], np.float32)],
    ref=lambda a: np.isnan(a), atol=0)

# --- elementwise binaries
CASES["elementwise_add"] = binary(np.add)
CASES["elementwise_sub"] = binary(np.subtract)
CASES["elementwise_mul"] = binary(np.multiply)
CASES["elementwise_div"] = binary(np.divide, lo=0.5, hi=2.0)
CASES["elementwise_max"] = binary(np.maximum, grad=())
CASES["elementwise_min"] = binary(np.minimum, grad=())
CASES["elementwise_pow"] = binary(np.power, lo=0.5, hi=2.0, grad=())
CASES["elementwise_mod"] = C(
    lambda: [I((2, 3), 17, 1, np.int32) + 1, I((2, 3), 5, 2, np.int32) + 1],
    ref=lambda a, b: np.mod(a, b), atol=0)
CASES["elementwise_floordiv"] = C(
    lambda: [I((2, 3), 17, 1, np.int32) + 1, I((2, 3), 5, 2, np.int32) + 1],
    ref=lambda a, b: a // b, atol=0)
CASES["atan2"] = binary(np.arctan2)
CASES["maximum_alias_check"] = None  # placeholder removed below
del CASES["maximum_alias_check"]

# --- comparisons / logical / bitwise
CASES["equal"] = compare(np.equal)
CASES["not_equal"] = compare(np.not_equal)
CASES["less_than"] = compare(np.less)
CASES["less_equal"] = compare(np.less_equal)
CASES["greater_than"] = compare(np.greater)
CASES["greater_equal"] = compare(np.greater_equal)
CASES["equal_all"] = C(lambda: [F((2, 3), 1), F((2, 3), 1)],
                       ref=lambda a, b: np.array(np.array_equal(a, b)),
                       atol=0, static=False)
CASES["allclose"] = C(lambda: [F((2, 3), 1), F((2, 3), 1)],
                      ref=lambda a, b: np.array(np.allclose(a, b)),
                      atol=0, static=False)
CASES["logical_and"] = logical(np.logical_and)
CASES["logical_or"] = logical(np.logical_or)
CASES["logical_xor"] = logical(np.logical_xor)
CASES["logical_not"] = logical(np.logical_not, n=1)
CASES["bitwise_and"] = bitwise(np.bitwise_and)
CASES["bitwise_or"] = bitwise(np.bitwise_or)
CASES["bitwise_xor"] = bitwise(np.bitwise_xor)
CASES["bitwise_not"] = bitwise(np.invert, n=1)

# --- reductions
CASES["reduce_sum"] = C(lambda: [F((2, 3), 3)], ref=lambda a: a.sum(),
                        grad=(0,))
CASES["reduce_mean"] = C(lambda: [F((2, 3), 3)], ref=lambda a: a.mean(),
                         grad=(0,))
CASES["mean"] = CASES["reduce_mean"]
CASES["reduce_max"] = C(lambda: [F((2, 3), 3)], ref=lambda a: a.max())
CASES["reduce_min"] = C(lambda: [F((2, 3), 3)], ref=lambda a: a.min())
CASES["reduce_prod"] = C(lambda: [F((2, 3), 3, 0.5, 1.5)],
                         ref=lambda a: a.prod())
CASES["reduce_all"] = C(lambda: [F((2, 3), 1) > -2], ref=lambda a: a.all(),
                        atol=0, static=False)
CASES["reduce_any"] = C(lambda: [F((2, 3), 1) > 0], ref=lambda a: a.any(),
                        atol=0, static=False)
CASES["logsumexp"] = C(lambda: [F((2, 3), 3)],
                       ref=lambda a: np.log(np.exp(a.astype(
                           np.float64)).sum()), grad=(0,))
CASES["l1_norm"] = C(lambda: [F((2, 3), 3)],
                     ref=lambda a: np.abs(a).sum())
CASES["squared_l2_norm"] = C(lambda: [F((2, 3), 3)],
                             ref=lambda a: np.square(a).sum(), grad=(0,))
CASES["p_norm"] = C(lambda: [F((2, 3), 3)],
                    ref=lambda a: np.sqrt(np.square(
                        a.astype(np.float64)).sum()))
CASES["norm"] = C(
    lambda: [F((2, 4), 3, 0.1, 1.0)],
    ref=lambda a: a / np.sqrt(np.square(a).sum(1, keepdims=True)),
    grad=(0,))

# --- linalg
CASES["matmul"] = C(lambda: [F((2, 3), 1), F((3, 4), 2)],
                    ref=lambda a, b: a @ b, grad=(0, 1))
CASES["matmul_v2"] = CASES["matmul"]
CASES["mul"] = CASES["matmul"]
CASES["bmm"] = C(lambda: [F((2, 2, 3), 1), F((2, 3, 2), 2)],
                 ref=lambda a, b: a @ b, grad=(0, 1))
CASES["mv"] = C(lambda: [F((3, 4), 1), F((4,), 2)],
                ref=lambda a, b: a @ b, grad=(0, 1))
CASES["dot"] = C(lambda: [F((4,), 1), F((4,), 2)],
                 ref=lambda a, b: np.dot(a, b), grad=(0, 1))
CASES["addmm"] = C(lambda: [F((2, 4), 1), F((2, 3), 2), F((3, 4), 3)],
                   ref=lambda i, x, y: i + x @ y, grad=(1, 2))
CASES["cholesky"] = C(lambda: [_psd()],
                      ref=lambda a: np.linalg.cholesky(
                          a.astype(np.float64)), rtol=1e-3)
CASES["inverse"] = C(lambda: [_psd(3, 5)],
                     ref=lambda a: np.linalg.inv(a.astype(np.float64)),
                     rtol=1e-3)
CASES["cross"] = C(lambda: [F((2, 3), 1), F((2, 3), 2)],
                   ref=lambda a, b: np.cross(a, b), grad=(0, 1))
CASES["kron"] = C(lambda: [F((2, 2), 1), F((2, 2), 2)],
                  ref=lambda a, b: np.kron(a, b))
CASES["trace"] = C(lambda: [F((3, 3), 1)], ref=lambda a: np.trace(a),
                   grad=(0,))
CASES["t"] = C(lambda: [F((2, 3), 1)], ref=lambda a: a.T)
CASES["transpose2"] = C(lambda: [F((2, 3, 4), 1)],
                        ref=lambda a: a.transpose(1, 0, 2),
                        kwargs={"perm": [1, 0, 2]}, grad=(0,))
CASES["tril_triu"] = C(lambda: [F((3, 3), 1)], ref=lambda a: np.tril(a))
CASES["diag"] = C(lambda: [F((4,), 1)], ref=lambda a: np.diag(a))
CASES["diag_v2"] = CASES["diag"]
CASES["diag_embed"] = C(lambda: [F((2, 3), 1)],
                        check=lambda got, args: got[0].shape == (2, 3, 3),
                        static=False)
CASES["diagonal"] = C(lambda: [F((3, 3), 1)],
                      ref=lambda a: np.diagonal(a))
CASES["dist"] = C(lambda: [F((2, 3), 1), F((2, 3), 2)],
                  ref=lambda a, b: np.sqrt(np.square(
                      (a - b).astype(np.float64)).sum()))
CASES["fsp"] = C(
    lambda: [F((1, 2, 3, 3), 1), F((1, 4, 3, 3), 2)],
    ref=lambda x, y: np.einsum("nchw,ndhw->ncd", x, y) / 9.0, rtol=1e-3)
CASES["bilinear_tensor_product"] = finite(
    lambda: [F((2, 3), 1), F((2, 4), 2), F((5, 3, 4), 3)])

# --- activations
CASES["relu"] = unary(lambda a: np.maximum(a, 0))
CASES["relu6"] = unary(lambda a: np.clip(a, 0, 6), lo=-2, hi=8)
CASES["leaky_relu"] = unary(lambda a: np.where(a > 0, a, 0.01 * a))
CASES["elu"] = unary(lambda a: np.where(a > 0, a, np.expm1(a)))
CASES["selu"] = unary(
    lambda a: 1.0507009873554805 * np.where(
        a > 0, a, 1.6732632423543772 * np.expm1(a)))
CASES["gelu"] = unary(
    lambda a: a * 0.5 * (1 + _scipy_erf(a / np.sqrt(2.0))), rtol=1e-3)
CASES["softplus"] = unary(np.logaddexp and (lambda a: np.log1p(np.exp(a))))
CASES["softsign"] = unary(lambda a: a / (1 + np.abs(a)))
CASES["softshrink"] = unary(
    lambda a: np.where(a > 0.5, a - 0.5, np.where(a < -0.5, a + 0.5, 0.0)),
    lo=-2, hi=2)
CASES["tanh_shrink"] = unary(lambda a: a - np.tanh(a))
CASES["stanh"] = unary(
    lambda a: 1.7159 * np.tanh(0.67 * a), rtol=1e-3)
CASES["hard_sigmoid"] = unary(
    lambda a: np.clip(a / 6.0 + 0.5, 0, 1), lo=-8, hi=8, grad=False)
CASES["hard_swish"] = unary(
    lambda a: a * np.clip(a / 6.0 + 0.5, 0, 1), lo=-8, hi=8, grad=False)
CASES["hard_tanh"] = unary(lambda a: np.clip(a, -1, 1), lo=-2, hi=2,
                           grad=False)
CASES["mish"] = unary(
    lambda a: a * np.tanh(np.log1p(np.exp(a))), rtol=1e-3)
CASES["swish_placeholder"] = None
del CASES["swish_placeholder"]
CASES["maxout"] = C(
    lambda: [F((1, 4, 2, 2), 1)], kwargs={"groups": 2},
    # maxouting.cc:44: out[c] = max over ADJACENT in[c*groups + ph]
    ref=lambda x: x.reshape(1, 2, 2, 2, 2).max(axis=2))
CASES["prelu"] = C(
    lambda: [F((1, 2, 2, 2), 1), F((2,), 2, 0.1, 0.3)],
    ref=lambda x, w: np.where(x > 0, x, x * w.reshape(1, 2, 1, 1)))
CASES["softmax"] = C(lambda: [F((2, 4), 1)], ref=lambda a: _np_softmax(a),
                     grad=(0,))
CASES["log_softmax"] = C(lambda: [F((2, 4), 1)],
                         ref=lambda a: np.log(_np_softmax(
                             a.astype(np.float64))), grad=(0,))
def _seq_sm_ref(x, L):
    out = np.zeros_like(x)
    for i, n in enumerate(L):
        out[i, :n] = _np_softmax(x[i, :n])
    return out


CASES["sequence_softmax"] = C(
    lambda: [F((2, 4), 1), np.array([3, 2], np.int64)],
    ref=_seq_sm_ref)
CASES["fused_softmax_mask_upper_triangle"] = C(
    lambda: [F((1, 1, 4, 4), 1)],
    check=lambda got, args: np.allclose(
        np.triu(got[0][0, 0], 1), 0, atol=1e-6),
    static=False)

# --- shape / manipulation
CASES["cast"] = C(lambda: [F((2, 3), 1)], kwargs={"dtype": "float64"},
                  ref=lambda a: a.astype(np.float64), static=False)
CASES["concat"] = C(lambda: [[F((2, 2), 1), F((2, 2), 2)]],
                    ref=lambda xs: np.concatenate(xs, 0), static=False)
CASES["stack"] = C(lambda: [[F((2, 2), 1), F((2, 2), 2)]],
                   ref=lambda xs: np.stack(xs, 0), static=False)
CASES["split"] = C(lambda: [F((4, 2), 1)],
                   kwargs={"num_or_sections": 2},
                   ref=lambda a: list(np.split(a, 2, 0)), static=False)
CASES["slice"] = C(lambda: [F((4, 3), 1)],
                   kwargs={"axes": [0], "starts": [1], "ends": [3]},
                   ref=lambda a: a[1:3])
CASES["strided_slice"] = C(
    lambda: [F((6, 3), 1)],
    kwargs={"axes": [0], "starts": [0], "ends": [6], "strides": [2]},
    ref=lambda a: a[0:6:2])
CASES["reshape2"] = C(lambda: [F((2, 6), 1)], kwargs={"shape": [3, 4]},
                      ref=lambda a: a.reshape(3, 4), grad=(0,))
CASES["squeeze2"] = C(lambda: [F((2, 1, 3), 1)],
                      ref=lambda a: a.reshape(2, 3))
CASES["unsqueeze2"] = C(lambda: [F((2, 3), 1)], kwargs={"axis": 0},
                        ref=lambda a: a[None])
CASES["flatten2"] = C(lambda: [F((2, 3, 4), 1)],
                      kwargs={"start_axis": 1},
                      ref=lambda a: a.reshape(2, 12))
CASES["flatten_contiguous_range"] = CASES["flatten2"]
CASES["flip"] = C(lambda: [F((2, 3), 1)], kwargs={"axis": [0]},
                  ref=lambda a: np.flip(a, 0))
CASES["reverse"] = C(lambda: [F((2, 3), 1)], kwargs={"axis": [0]},
                     ref=lambda a: np.flip(a, 0))
CASES["roll"] = C(lambda: [F((2, 3), 1)], kwargs={"shifts": 1},
                  ref=lambda a: np.roll(a.reshape(-1), 1).reshape(a.shape))
CASES["tile"] = C(lambda: [F((2, 2), 1)], kwargs={"repeat_times": [2, 1]},
                  ref=lambda a: np.tile(a, (2, 1)))
CASES["expand_v2"] = C(lambda: [F((1, 3), 1)], kwargs={"shape": [4, 3]},
                       ref=lambda a: np.broadcast_to(a, (4, 3)))
CASES["expand_as_v2"] = C(lambda: [F((1, 3), 1), F((4, 3), 2)],
                          ref=lambda a, b: np.broadcast_to(a, b.shape))
CASES["broadcast_tensors"] = C(
    lambda: [[F((1, 3), 1), F((4, 1), 2)]],
    ref=lambda xs: list(np.broadcast_arrays(*xs)), static=False)
CASES["unbind"] = C(lambda: [F((2, 3), 1)],
                    ref=lambda a: [a[0], a[1]], static=False)
CASES["unstack"] = CASES["unbind"]
CASES["gather"] = C(lambda: [F((4, 3), 1), np.array([0, 2], np.int64)],
                    ref=lambda a, i: a[i], grad=(0,))
CASES["gather_nd"] = C(
    lambda: [F((3, 3), 1), np.array([[0, 1], [2, 2]], np.int64)],
    ref=lambda a, i: a[tuple(i.T)])
CASES["index_select"] = C(
    lambda: [F((4, 3), 1), np.array([0, 2], np.int64)],
    ref=lambda a, i: a[i])
CASES["index_sample"] = C(
    lambda: [F((2, 4), 1), np.array([[0, 1], [2, 3]], np.int64)],
    ref=lambda a, i: np.take_along_axis(a, i, 1))
CASES["masked_select"] = C(
    lambda: [np.arange(6, dtype=np.float32).reshape(2, 3),
             np.tile(np.array([True, False, True]), (2, 1))],
    ref=lambda a, m: a[m], static=False)
CASES["where"] = C(
    lambda: [F((2, 3), 1) > 0, F((2, 3), 2), F((2, 3), 3)],
    ref=lambda c, a, b: np.where(c, a, b))
CASES["where_index"] = C(
    lambda: [np.array([0.0, 1.0, 0.0, 2.0], np.float32)],
    ref=lambda a: np.array([[1], [3]], np.int64), atol=0, static=False)
CASES["scatter"] = C(
    lambda: [np.zeros((4, 2), np.float32), np.array([1, 3], np.int64),
             F((2, 2), 2)],
    ref=lambda x, i, u: _np_scatter(x, i, u))
CASES["scatter_nd_add"] = C(
    lambda: [np.ones((4,), np.float32), np.array([[1], [1]], np.int64),
             np.array([1.0, 2.0], np.float32)],
    ref=lambda x, i, u: np.array([1.0, 4.0, 1.0, 1.0]))
CASES["shard_index"] = C(
    lambda: [np.array([[1], [5]], np.int64)],
    kwargs={"index_num": 8, "nshards": 2, "shard_id": 0},
    ref=lambda a: np.array([[1], [-1]], np.int64), atol=0)
CASES["shape"] = C(lambda: [F((2, 3), 1)],
                   ref=lambda a: np.array([2, 3]), atol=0, static=False)
CASES["size"] = C(lambda: [F((2, 3), 1)], ref=lambda a: np.array(6),
                  atol=0, static=False)
CASES["increment"] = C(lambda: [np.array([1.5], np.float32)],
                       ref=lambda a: a + 1.0)
CASES["assign"] = C(lambda: [F((2, 3), 1)], ref=lambda a: a)
CASES["share_data"] = C(lambda: [F((2, 3), 1)], ref=lambda a: a)
CASES["memcpy"] = C(lambda: [F((2, 3), 1)], ref=lambda a: a,
                    static=False)
CASES["meshgrid"] = C(
    lambda: [np.arange(2, dtype=np.float32),
             np.arange(3, dtype=np.float32)],
    ref=lambda a, b: list(np.meshgrid(a, b, indexing="ij")), static=False)
CASES["multiplex"] = C(
    lambda: [[F((2, 3), 1), F((2, 3), 2)], np.array([[0], [1]], np.int64)],
    ref=lambda ins, idx: np.stack([ins[i[0]][r]
                                   for r, i in enumerate(idx)]),
    static=False)
CASES["crop"] = C(lambda: [F((3, 4), 1)],
                  kwargs={"shape": [2, 2], "offsets": [1, 1]},
                  ref=lambda a: a[1:3, 1:3])
CASES["crop_tensor"] = CASES["crop"]
CASES["pad"] = C(lambda: [F((2, 2), 1)], kwargs={"pad": [1, 1, 0, 0]},
                 check=lambda got, args: got[0].shape == (4, 2),
                 static=False)
CASES["pad2d"] = CASES["pad"]
CASES["pad3d"] = CASES["pad"]
CASES["pad_constant_like"] = C(
    lambda: [np.zeros((3, 3), np.float32), F((2, 2), 1)],
    check=lambda got, args: got[0].shape == (3, 3), static=False)
CASES["unfold"] = C(lambda: [F((1, 1, 3, 3), 1)],
                    kwargs={"kernel_sizes": 2},
                    check=lambda got, args: got[0].shape == (1, 4, 4),
                    static=False)
CASES["unique"] = C(lambda: [np.array([3.0, 1.0, 3.0, 2.0], np.float32)],
                    ref=lambda a: np.unique(a), static=False)
CASES["unique_with_counts"] = C(
    lambda: [np.array([3.0, 1.0, 3.0], np.float32)],
    check=lambda got, args: len(got) >= 2, static=False)
CASES["partial_concat"] = C(
    lambda: [[F((2, 4), 1), F((2, 4), 2)]],
    kwargs={"start_index": 0, "length": 2},
    check=lambda got, args: got[0].shape == (2, 4), static=False)
CASES["partial_sum"] = C(
    lambda: [[F((2, 4), 1), F((2, 4), 2)]],
    kwargs={"start_index": 0, "length": 2},
    check=lambda got, args: got[0].shape == (2, 2), static=False)
CASES["coalesce_tensor"] = C(
    lambda: [[F((2,), 1), F((3,), 2)]],
    check=lambda got, args: sum(g.size for g in got) >= 5, static=False)
CASES["tensor_array_to_tensor"] = C(
    lambda: [[F((2, 2), 1), F((2, 2), 2)]],
    check=lambda got, args: got[0].shape[0] == 4, static=False)
CASES["sum"] = C(lambda: [[F((2, 3), 1), F((2, 3), 2)]],
                 ref=lambda xs: xs[0] + xs[1], static=False)

# --- creation / random
CASES["fill_constant"] = C(lambda: [[2, 3], 1.5],
                           ref=lambda s, v: np.full(s, v, np.float32),
                           static=False)
CASES["fill_constant_batch_size_like"] = CASES["fill_constant"]
CASES["fill_any_like"] = C(lambda: [F((2, 3), 1), 2.0],
                           ref=lambda a, v: np.full_like(a, v),
                           static=False)
CASES["fill_zeros_like"] = C(lambda: [F((2, 3), 1)],
                             ref=lambda a: np.zeros_like(a), static=False)
CASES["empty"] = shape_is(lambda: [[2, 3]], (2, 3))
CASES["eye"] = C(lambda: [3], ref=lambda n: np.eye(n), static=False)
CASES["linspace"] = C(lambda: [0.0, 1.0, 5],
                      ref=lambda a, b, n: np.linspace(a, b, n),
                      static=False)
CASES["range"] = C(lambda: [0, 6, 2], ref=lambda a, b, s: np.arange(a, b, s),
                   static=False)
CASES["assign_value"] = C(
    lambda: [[2, 2], "float32", [1.0, 2.0, 3.0, 4.0]],
    ref=lambda s, d, v: np.array(v, d).reshape(s), static=False)
CASES["gaussian_random"] = prop(
    lambda: [], lambda got, args: got[0].shape == (64, 64),
    kwargs={"shape": [64, 64]})
CASES["truncated_gaussian_random"] = CASES["gaussian_random"]
CASES["gaussian_random_batch_size_like"] = shape_is(
    lambda: [F((4, 3), 1), [4, 5]], (4, 5))
CASES["uniform_random"] = prop(
    lambda: [[32, 32]],
    lambda got, args: got[0].shape == (32, 32)
    and (got[0] >= -1).all() and (got[0] <= 1).all())
CASES["uniform_random_batch_size_like"] = shape_is(
    lambda: [F((4, 3), 1), [4, 5]], (4, 5))
CASES["randint"] = prop(
    lambda: [0, 10], lambda got, args: got[0].dtype in (np.int32, np.int64),
    kwargs={"shape": [8]})
CASES["randperm"] = prop(
    lambda: [6],
    lambda got, args: sorted(got[0].tolist()) == list(range(6)))
CASES["bernoulli"] = prop(
    lambda: [np.full((64,), 0.5, np.float32)],
    lambda got, args: set(np.unique(got[0])) <= {0.0, 1.0})
CASES["multinomial"] = prop(
    lambda: [np.array([0.2, 0.8], np.float32)],
    lambda got, args: got[0].shape == (1,), kwargs={"num_samples": 1})
CASES["sampling_id"] = prop(
    lambda: [F((4, 3), 1, 0.0, 1.0)],
    lambda got, args: got[0].shape == (4,))
CASES["seed"] = prop(lambda: [7], lambda got, args: True)
CASES["random_crop"] = shape_is(lambda: [F((1, 3, 5, 5), 1), [1, 3, 3, 3]],
                                (1, 3, 3, 3))

# --- nn core
CASES["conv2d"] = C(
    lambda: [F((1, 1, 3, 3), 1), F((1, 1, 2, 2), 2)],
    ref=lambda x, w: _np_conv2d(x, w), grad=(0, 1))
CASES["conv3d"] = finite(lambda: [F((1, 1, 3, 3, 3), 1),
                                  F((1, 1, 2, 2, 2), 2)])
CASES["conv2d_transpose"] = finite(lambda: [F((1, 1, 2, 2), 1),
                                            F((1, 1, 2, 2), 2)])
CASES["conv3d_transpose"] = finite(lambda: [F((1, 1, 2, 2, 2), 1),
                                            F((1, 1, 2, 2, 2), 2)])
def _conv_shift_ref(x, y):
    # conv_shift_op.cc:125: circular Out[i] = sum_j X_{i+j} Y_j
    n = y.shape[1]
    half = (n - 1) // 2
    out = np.zeros_like(x)
    for i in range(x.shape[1]):
        for j in range(-half, half + 1):
            out[:, i] += x[:, (i + j) % x.shape[1]] * y[:, j + half]
    return out


CASES["conv_shift"] = C(lambda: [F((2, 5), 1), F((2, 3), 2)],
                        ref=_conv_shift_ref, rtol=1e-3)
CASES["deformable_conv"] = finite(
    lambda: [F((1, 1, 3, 3), 1), F((1, 8, 2, 2), 2), F((1, 1, 2, 2), 3)])
CASES["deformable_conv_v1"] = CASES["deformable_conv"]
CASES["pool2d"] = C(lambda: [F((1, 1, 4, 4), 1)], kwargs={"kernel_size": 2},
                    ref=lambda x: _np_maxpool2(x), grad=(0,))
CASES["pool2d_avg"] = C(lambda: [F((1, 1, 4, 4), 1)],
                        kwargs={"kernel_size": 2},
                        ref=lambda x: _np_avgpool2(x), grad=(0,))
CASES["pool3d"] = C(lambda: [F((1, 1, 2, 2, 2), 1)],
                    kwargs={"kernel_size": 2},
                    ref=lambda x: x.max().reshape(1, 1, 1, 1, 1))
CASES["max_pool2d_with_index"] = C(
    lambda: [F((1, 1, 4, 4), 1)], kwargs={"kernel_size": 2},
    check=lambda got, args: got[0].shape == (1, 1, 2, 2)
    and len(got) >= 2, static=False)
CASES["unpool"] = finite(
    lambda: [F((1, 1, 2, 2), 1), I((1, 1, 2, 2), 16, 2),
             2])
CASES["spp"] = finite(lambda: [F((1, 2, 4, 4), 1)])
CASES["batch_norm"] = C(
    lambda: [F((2, 3, 2, 2), 1),
             np.array([0.1, -0.2, 0.3], np.float32),
             np.array([0.5, 2.0, 1.2], np.float32),
             np.array([1.5, 0.7, -1.0], np.float32),
             np.array([-0.2, 0.4, 0.0], np.float32)],
    ref=lambda x, rm, rv, w, b: (x - rm.reshape(1, 3, 1, 1))
    / np.sqrt(rv.reshape(1, 3, 1, 1) + 1e-5) * w.reshape(1, 3, 1, 1)
    + b.reshape(1, 3, 1, 1), rtol=1e-3)
CASES["instance_norm"] = C(
    lambda: [F((2, 3, 2, 2), 1)],
    ref=lambda x: (x - x.mean(axis=(2, 3), keepdims=True))
    / np.sqrt(x.var(axis=(2, 3), keepdims=True) + 1e-5), rtol=1e-3)
def _gn_ref(x, g):
    xr = x.reshape(x.shape[0], g, -1)
    m = xr.mean(axis=2, keepdims=True)
    v = xr.var(axis=2, keepdims=True)
    return ((xr - m) / np.sqrt(v + 1e-5)).reshape(x.shape)


CASES["group_norm"] = C(lambda: [F((2, 4, 2, 2), 1), 2], ref=_gn_ref,
                        rtol=1e-3)
CASES["layer_norm"] = C(
    lambda: [F((2, 4), 1)], kwargs={"normalized_shape": 4},
    ref=lambda a: (a - a.mean(-1, keepdims=True)) / np.sqrt(
        a.var(-1, keepdims=True) + 1e-5), rtol=1e-3, grad=(0,))
def _data_norm_ref(x, bs, bsum, bsq):
    # data_norm_op.cc:303: scales = sqrt(batch_size / batch_square_sum)
    return (x - (bsum / bs)[None]) * np.sqrt(bs / bsq)[None]


CASES["data_norm"] = C(
    lambda: [F((2, 3), 1), np.full((3,), 4.0, np.float32),
             F((3,), 2), np.full((3,), 6.0, np.float32)],
    ref=_data_norm_ref, rtol=1e-3)
def _lrn_ref(x, size):
    sq = np.zeros_like(x)
    c_all = x.shape[1]
    for c in range(c_all):
        lo, hi = max(0, c - size // 2), min(c_all, c + size // 2 + 1)
        sq[:, c] = (x[:, lo:hi] ** 2).sum(1)
    return x / (1.0 + 1e-4 * sq) ** 0.75


CASES["lrn"] = C(lambda: [F((1, 4, 2, 2), 1), 3], ref=_lrn_ref,
                 rtol=1e-3)
CASES["dropout"] = C(lambda: [F((2, 3), 1)], kwargs={"p": 0.0},
                     ref=lambda a: a, grad=(0,), static=False)
CASES["lookup_table"] = C(
    lambda: [np.array([0, 2], np.int64), F((4, 3), 1)],
    ref=lambda i, w: w[i], grad=(1,))
CASES["lookup_table_v2"] = CASES["lookup_table"]
CASES["one_hot"] = C(lambda: [np.array([0, 2], np.int64)],
                     kwargs={"num_classes": 4},
                     ref=lambda a: np.eye(4)[a], atol=0)
CASES["one_hot_v2"] = CASES["one_hot"]
CASES["pixel_shuffle"] = C(
    lambda: [F((1, 4, 2, 2), 1)], kwargs={"upscale_factor": 2},
    check=lambda got, args: got[0].shape == (1, 1, 4, 4), static=False)
def _shufflech_ref(x, g=2):
    # shuffle_channel_op.h:46: out[j*g + i] = in[i*(C/g) + j]
    out = np.empty_like(x)
    cpg = x.shape[1] // g
    for i in range(g):
        for j in range(cpg):
            out[:, j * g + i] = x[:, i * cpg + j]
    return out


# non-square split (g=2, C/g=3) so the transpose direction is pinned
CASES["shuffle_channel"] = C(
    lambda: [F((1, 6, 2, 2), 1)], kwargs={"group": 2}, ref=_shufflech_ref)
def _s2d_ref(x, bs=2):
    # space_to_depth_op.h:48-51 index math, written as explicit loops so
    # the oracle is independent of the kernel's reshape/transpose recipe:
    # out[b, offset*C + c, h, w] = x[b, c, h*bs + offset//bs, w*bs + offset%bs]
    B, C, H, W = x.shape
    out = np.empty((B, C * bs * bs, H // bs, W // bs), x.dtype)
    for off in range(bs * bs):
        for c in range(C):
            out[:, off * C + c] = x[:, c, off // bs::bs, off % bs::bs]
    return out


CASES["space_to_depth"] = C(
    lambda: [F((1, 2, 4, 4), 1)], kwargs={"blocksize": 2}, ref=_s2d_ref)
def _tshift_ref(x, seg):
    n = x.shape[0] // seg
    xr = x.reshape(n, seg, *x.shape[1:])
    fold = x.shape[1] // 4
    out = np.zeros_like(xr)
    out[:, :-1, :fold] = xr[:, 1:, :fold]
    out[:, 1:, fold:2 * fold] = xr[:, :-1, fold:2 * fold]
    out[:, :, 2 * fold:] = xr[:, :, 2 * fold:]
    return out.reshape(x.shape)


CASES["temporal_shift"] = C(lambda: [F((4, 4, 2, 2), 1), 2],
                            ref=_tshift_ref)
CASES["interpolate"] = C(
    lambda: [F((1, 1, 2, 2), 1)], kwargs={"size": [4, 4]},
    check=lambda got, args: got[0].shape == (1, 1, 4, 4), static=False)
CASES["interpolate_v2"] = CASES["interpolate"]
CASES["grid_sampler"] = finite(
    lambda: [F((1, 1, 3, 3), 1), F((1, 2, 2, 2), 2)])
CASES["affine_grid"] = shape_is(
    lambda: [F((1, 2, 3), 1), [1, 1, 2, 2]], (1, 2, 2, 2))
CASES["affine_channel"] = C(
    lambda: [F((1, 2, 2, 2), 1), F((2,), 2), F((2,), 3)],
    ref=lambda x, s, b: x * s.reshape(1, 2, 1, 1) + b.reshape(1, 2, 1, 1))
CASES["im2sequence"] = C(
    lambda: [F((1, 1, 4, 4), 1)], kwargs={"filter_size": 2, "stride": 2},
    ref=lambda x: np.stack([x[0, 0, r:r + 2, c:c + 2].reshape(-1)
                            for r in (0, 2) for c in (0, 2)]),
    static=False)
CASES["spectral_norm"] = prop(
    lambda: [F((4, 3), 1)],
    lambda got, args: np.isfinite(got[0]).all()
    and np.linalg.norm(got[0], 2) < np.linalg.norm(args[0], 2) + 1.0)
CASES["clip"] = C(lambda: [F((2, 3), 1)],
                  kwargs={"min": -0.5, "max": 0.5},
                  ref=lambda a: np.clip(a, -0.5, 0.5), grad=(0,))
CASES["clip_by_norm"] = C(
    lambda: [F((2, 3), 1)], kwargs={"max_norm": 0.1},
    ref=lambda a: a * (0.1 / max(0.1, np.sqrt(np.square(a).sum()))),
    rtol=1e-3)
CASES["scale"] = C(lambda: [F((2, 3), 1)],
                   kwargs={"scale": 2.0, "bias": 1.0},
                   ref=lambda a: 2 * a + 1, grad=(0,))
CASES["label_smooth"] = C(
    lambda: [np.eye(3, dtype=np.float32)],
    ref=lambda a: a * 0.9 + 0.1 / 3, rtol=1e-3)
CASES["add_position_encoding"] = finite(lambda: [F((2, 4, 6), 1)])

# --- losses
CASES["cross_entropy"] = finite(
    lambda: [F((3, 4), 1), I((3,), 4, 2)], min_outputs=1)
CASES["softmax_with_cross_entropy"] = C(
    lambda: [F((3, 4), 1), I((3, 1), 4, 2)],
    ref=lambda lg, l: -np.take_along_axis(
        np.log(_np_softmax(lg.astype(np.float64))), l, 1),
    grad=(0,))
CASES["sigmoid_cross_entropy_with_logits"] = finite(
    lambda: [F((2, 3), 1), (F((2, 3), 2) > 0).astype(np.float32)])
CASES["bce_loss"] = C(
    lambda: [F((2, 3), 1, 0.1, 0.9), (F((2, 3), 2) > 0).astype(np.float32)],
    ref=lambda pv, l: np.mean(-l * np.log(pv) - (1 - l) * np.log(1 - pv)),
    rtol=1e-3)
CASES["nll_loss"] = C(
    lambda: [np.log(_SM(F((3, 4), 1))), I((3,), 4, 2)],
    ref=lambda lp, l: -np.mean(np.take_along_axis(
        lp.astype(np.float64), l[:, None], 1)))
CASES["kldiv_loss"] = C(
    lambda: [np.log(_SM(F((2, 4), 1))), _SM(F((2, 4), 2)).astype(np.float32)],
    ref=lambda lp, l: np.mean(l * (np.log(l) - lp)), rtol=1e-3)
CASES["log_loss"] = C(
    lambda: [F((3, 1), 1, 0.1, 0.9), (F((3, 1), 2) > 0).astype(np.float32)],
    ref=lambda pv, l: -l * np.log(pv + 1e-4)
    - (1 - l) * np.log(1 - pv + 1e-4), rtol=1e-3)
CASES["hinge_loss"] = C(
    lambda: [F((3, 1), 1), (F((3, 1), 2) > 0).astype(np.float32)],
    ref=lambda x, l: np.maximum(0.0, 1 - (2 * l - 1) * x))
CASES["huber_loss"] = C(
    lambda: [F((3, 1), 1), F((3, 1), 2)],
    ref=lambda x, y: np.where(np.abs(x - y) <= 1.0, 0.5 * (x - y) ** 2,
                              np.abs(x - y) - 0.5))
CASES["smooth_l1_loss"] = C(
    lambda: [F((3, 2), 1), F((3, 2), 2)],
    ref=lambda x, y: np.mean(np.where(np.abs(x - y) < 1.0,
                                      0.5 * (x - y) ** 2,
                                      np.abs(x - y) - 0.5)))
CASES["margin_rank_loss"] = C(
    lambda: [F((3, 1), 1), F((3, 1), 2),
             np.sign(F((3, 1), 3)).astype(np.float32)],
    ref=lambda a, b, l: np.mean(np.maximum(0.0, -l * (a - b))))
CASES["rank_loss"] = C(
    lambda: [(F((3, 1), 1) > 0).astype(np.float32), F((3, 1), 2),
             F((3, 1), 3)],
    ref=lambda l, a, b: np.log1p(np.exp(a - b)) - l * (a - b),
    rtol=1e-3)
CASES["bpr_loss"] = finite(lambda: [F((3, 4), 1), I((3, 1), 4, 2)])
CASES["center_loss"] = C(
    lambda: [F((3, 4), 1), I((3,), 5, 2), F((5, 4), 3)],
    # center_loss_op.h: per-sample 0.5*||x - center_{y}||^2
    ref=lambda x, y, c: 0.5 * ((x - c[y]) ** 2).sum(1, keepdims=True),
    static=False)
CASES["squared_l2_distance"] = C(
    lambda: [F((3, 4), 1), F((3, 4), 2)],
    ref=lambda a, b: np.square(a - b).sum(1))
CASES["modified_huber_loss"] = finite(
    lambda: [F((3, 1), 1), (F((3, 1), 2) > 0).astype(np.float32)])
CASES["teacher_student_sigmoid_loss"] = finite(
    lambda: [F((3, 1), 1), F((3, 1), 2, 0.0, 1.0)])
CASES["cos_sim"] = C(
    lambda: [F((2, 4), 1, 0.1, 1.0), F((2, 4), 2, 0.1, 1.0)],
    ref=lambda a, b: ((a * b).sum(1) / (
        np.linalg.norm(a, axis=1) * np.linalg.norm(b, axis=1))
    ).reshape(-1, 1), rtol=1e-3)
def _mean_iou_ref(pred, lab, n):
    ious = []
    for c in range(n):
        tp = ((pred == c) & (lab == c)).sum()
        denom = ((pred == c) | (lab == c)).sum()
        if denom:
            ious.append(tp / denom)
    return np.float32(np.mean(ious))


CASES["mean_iou"] = C(
    lambda: [I((4, 4), 3, 1, np.int32), I((4, 4), 3, 2, np.int32), 3],
    ref=_mean_iou_ref, rtol=1e-5, static=False)
CASES["hierarchical_sigmoid"] = finite(
    lambda: [F((3, 4), 1), I((3, 1), 6, 2), 6, F((5, 4), 3)])
CASES["nce"] = finite(
    lambda: [F((3, 4), 1), F((6, 4), 2), I((3, 1), 6, 3)],
    kwargs={"num_total_classes": 6, "num_neg_samples": 2})
CASES["warpctc"] = finite(
    lambda: [np.log(_SM(F((4, 2, 5), 1))).astype(np.float32),
             I((2, 3), 4, 2) + 1, np.array([4, 4], np.int64),
             np.array([3, 3], np.int64)])
CASES["sample_logits"] = finite(
    lambda: [F((3, 6), 1), I((3, 1), 6, 2), 3], min_outputs=1)

# --- metrics / eval
CASES["chunk_eval"] = finite(
    lambda: [I((1, 6), 3, 1), I((1, 6), 3, 2)], min_outputs=1)
CASES["edit_distance"] = C(
    lambda: [np.array([[1, 2, 3, 4]], np.int64),
             np.array([[1, 3, 3, 3]], np.int64)],
    ref=lambda a, b: np.array([[0.5]]), static=False)  # 2 edits / len 4
def _pnp_ref(score, label, qid):
    # oracle valid for a single query group only (the case feeds one)
    assert (qid == qid.ravel()[0]).all()
    pos = score[label.ravel() > 0].ravel()
    neg = score[label.ravel() <= 0].ravel()
    right = (pos[:, None] > neg[None, :]).sum()
    wrong = (pos[:, None] < neg[None, :]).sum()
    neutral = (pos[:, None] == neg[None, :]).sum()
    return [np.float32([right]), np.float32([wrong]),
            np.float32([neutral])]


CASES["positive_negative_pair"] = C(
    lambda: [F((4, 1), 1, 0.0, 1.0), (F((4, 1), 2) > 0).astype(np.float32),
             np.zeros((4, 1), np.int64)],
    ref=_pnp_ref, atol=0, static=False)
CASES["histogram"] = C(
    lambda: [np.array([0.1, 0.4, 0.6, 0.9], np.float32)],
    kwargs={"bins": 2, "min": 0.0, "max": 1.0},
    ref=lambda a: np.histogram(a, bins=2, range=(0, 1))[0], atol=0,
    static=False)
CASES["cumsum"] = C(lambda: [F((2, 3), 1)],
                    ref=lambda a: np.cumsum(a.reshape(-1)).reshape(2, 3)
                    if False else np.cumsum(a, None).astype(np.float64),
                    static=False)
CASES["cumprod"] = C(lambda: [F((2, 3), 1, 0.5, 1.5)], kwargs={"dim": 1},
                     ref=lambda a: np.cumprod(a, 1))
CASES["arg_max"] = C(lambda: [F((2, 4), 1)],
                     ref=lambda a: a.reshape(-1).argmax(), atol=0,
                     static=False)
CASES["arg_min"] = C(lambda: [F((2, 4), 1)],
                     ref=lambda a: a.reshape(-1).argmin(), atol=0,
                     static=False)
CASES["argsort"] = C(lambda: [F((2, 4), 1)],
                     check=lambda got, args: len(got) >= 1, static=False)
CASES["top_k"] = C(
    lambda: [np.array([[1.0, 3.0, 2.0]], np.float32)], kwargs={"k": 2},
    ref=lambda a: [np.array([[3.0, 2.0]]), np.array([[1, 2]])],
    atol=0, static=False)
CASES["top_k_v2"] = CASES["top_k"]
CASES["accuracy_placeholder"] = None
del CASES["accuracy_placeholder"]

# --- sequence / text
CASES["sequence_mask"] = C(
    lambda: [np.array([1, 3], np.int64)], kwargs={"maxlen": 4},
    ref=lambda l: (np.arange(4)[None] < l[:, None]).astype(np.int64),
    atol=0)
CASES["sequence_pad"] = finite(
    lambda: [F((5, 2), 1), np.array([2, 3], np.int64)], min_outputs=1)
CASES["sequence_unpad"] = C(
    lambda: [F((2, 4, 3), 1), np.array([2, 3], np.int64)],
    ref=lambda x, L: np.concatenate([x[i, :n] for i, n in enumerate(L)]),
    static=False)
CASES["sequence_pool"] = C(
    lambda: [F((2, 4, 3), 1), np.array([2, 3], np.int64)],
    ref=lambda x, L: np.stack([x[i, :n].mean(0)
                               for i, n in enumerate(L)]))
def _seq_rev_ref(x, L):
    out = x.copy()
    for i, n in enumerate(L):
        out[i, :n] = x[i, :n][::-1]
    return out


CASES["sequence_reverse"] = C(
    lambda: [F((2, 4, 3), 1), np.array([2, 3], np.int64)],
    ref=_seq_rev_ref)
CASES["sequence_expand"] = C(
    lambda: [F((2, 3), 1), np.array([2, 1], np.int64)],
    ref=lambda x, r: np.repeat(x, r, axis=0), static=False)
CASES["sequence_conv"] = finite(
    lambda: [F((2, 4, 3), 1), F((9, 5), 2), np.array([2, 3], np.int64)])
CASES["segment_pool"] = C(
    lambda: [F((4, 2), 1), np.array([0, 0, 1, 1], np.int64)],
    ref=lambda x, s: np.stack([x[:2].sum(0), x[2:].sum(0)]),
    kwargs={"pool_type": "SUM"}, static=False)
def _row_conv_ref(x, w):
    # row_conv_op.cc:197: out[k] += x[k+w] * filt[w] (future context)
    out = np.zeros_like(x)
    for k in range(x.shape[1]):
        for j in range(w.shape[0]):
            if k + j < x.shape[1]:
                out[:, k] += x[:, k + j] * w[j]
    return out


CASES["row_conv"] = C(lambda: [F((2, 4, 3), 1), F((3, 3), 2)],
                      ref=_row_conv_ref, rtol=1e-3)
CASES["beam_search"] = finite(
    lambda: [I((2, 1), 5, 1), F((2, 1), 2, 0.0, 1.0), I((2, 2), 5, 3),
             F((2, 2), 4, 0.0, 1.0), 2, 0], min_outputs=1)
CASES["beam_search_decode"] = finite(
    lambda: [[I((2, 2), 5, 1), I((2, 2), 5, 2)],
             [I((2, 2), 2, 3), I((2, 2), 2, 4)], 2, 0], min_outputs=1)
CASES["gather_tree"] = C(
    lambda: [I((3, 1, 2), 5, 1), np.zeros((3, 1, 2), np.int64)],
    check=lambda got, args: got[0].shape == (3, 1, 2), static=False)
CASES["ctc_align"] = C(
    lambda: [np.array([[1, 1, 0, 2, 2], [0, 3, 0, 0, 1]], np.int64)],
    ref=lambda x: [np.array([[1, 2, 0, 0, 0], [3, 1, 0, 0, 0]]),
                   np.array([[2], [2]])], atol=0, static=False)
CASES["linear_chain_crf"] = finite(
    lambda: [F((2, 4, 3), 1), F((5, 3), 2), I((2, 4), 3, 3),
             np.array([3, 4], np.int64)], min_outputs=1)
CASES["crf_decoding"] = C(
    lambda: [F((2, 4, 3), 1), F((5, 3), 2), np.array([3, 4], np.int64)],
    check=lambda got, args: got[0].shape[:2] == (2, 4), static=False)
CASES["edit_distance"] = CASES["edit_distance"]

# --- vision extras
CASES["roi_align"] = finite(
    lambda: [F((1, 1, 4, 4), 1),
             np.array([[0.0, 0.0, 3.0, 3.0]], np.float32),
             np.array([1], np.int32), 2])
CASES["roi_pool"] = finite(
    lambda: [F((1, 1, 4, 4), 1),
             np.array([[0.0, 0.0, 3.0, 3.0]], np.float32),
             np.array([1], np.int32), 2])
CASES["prroi_pool"] = finite(
    lambda: [F((1, 1, 4, 4), 1),
             np.array([[0.0, 0.0, 3.0, 3.0]], np.float32), 2, 2])
CASES["psroi_pool"] = finite(
    lambda: [F((1, 8, 4, 4), 1),
             np.array([[0.0, 0.0, 3.0, 3.0]], np.float32), 2, 1.0, 2, 2])
CASES["deformable_psroi_pooling"] = finite(
    lambda: [F((1, 8, 4, 4), 1),
             np.array([[0.0, 0.0, 3.0, 3.0]], np.float32)],
    kwargs={"no_trans": True, "output_dim": 2, "pooled_height": 2,
            "pooled_width": 2, "group_size": (2, 2)})
CASES["cvm"] = C(
    lambda: [F((2, 4), 1, 0.1, 1.0)],
    ref=lambda x: np.concatenate(
        [np.log(x[:, :1] + 1), np.log(x[:, 1:2] + 1) - np.log(x[:, :1] + 1),
         x[:, 2:]], axis=1), rtol=1e-3)
CASES["fused_elemwise_placeholder"] = None
del CASES["fused_elemwise_placeholder"]

# --- framework / misc
CASES["py_func"] = C(
    lambda: [np.square, F((2, 3), 1), [(2, 3)], ["float32"]],
    check=lambda got, args: np.allclose(got[0], np.square(args[1])),
    static=False)
def _make_selected_rows():
    from paddle_tpu.core.indexed_slices import IndexedSlices

    return [IndexedSlices(np.array([0, 2, 0], np.int64),
                          F((3, 2), 1), (4, 2))]


CASES["get_tensor_from_selected_rows"] = prop(
    _make_selected_rows,
    lambda got, args: got[0].shape == (4, 2) and np.isfinite(got[0]).all())
CASES["merge_selected_rows"] = prop(
    _make_selected_rows,
    lambda got, args: got[0].item().indices.shape[0] == 2)
CASES["average_accumulates"] = finite(
    lambda: [F((3,), 1), np.zeros(3, np.float32), np.zeros(3, np.float32),
             np.zeros(3, np.float32), np.array([0], np.int64),
             np.array([0], np.int64), np.array([1], np.int64),
             4, 16, 4], min_outputs=1)
CASES["lerp"] = C(
    lambda: [F((2, 3), 1), F((2, 3), 2), np.array(0.25, np.float32)],
    check=lambda got, args: np.allclose(
        got[0], args[0] + 0.25 * (args[1] - args[0]), atol=1e-5),
    static=False)


# ---------------------------------------------------------------------------
# numpy helpers used above

def _np_scatter(x, i, u):
    out = x.copy()
    out[i] = u
    return out


def _np_conv2d(x, w):
    n, cin, h, ww = x.shape
    co, _, kh, kw = w.shape
    out = np.zeros((n, co, h - kh + 1, ww - kw + 1), np.float64)
    for oc in range(co):
        for i in range(out.shape[2]):
            for j in range(out.shape[3]):
                out[:, oc, i, j] = (
                    x[:, :, i:i + kh, j:j + kw].astype(np.float64)
                    * w[oc].astype(np.float64)).sum(axis=(1, 2, 3))
    return out


def _np_maxpool2(x):
    n, c, h, w = x.shape
    return x.reshape(n, c, h // 2, 2, w // 2, 2).max(axis=(3, 5))


def _np_avgpool2(x):
    n, c, h, w = x.shape
    return x.reshape(n, c, h // 2, 2, w // 2, 2).mean(axis=(3, 5))


def _scipy_erf(a):
    from scipy.special import erf as _e

    return _e(a)


def _scipy_digamma(a):
    from scipy.special import digamma as _d

    return _d(a)


def _scipy_gammaln(a):
    from scipy.special import gammaln as _g

    return _g(a)


# ---------------------------------------------------------------------------

_NAMES = sorted(_C_ops.op_names())


@pytest.mark.parametrize("name", _NAMES)
def test_golden(name):
    c = CASES.get(name)
    assert c is not None, f"no golden case for served op {name!r}"
    args, got = _run_eager(name, c)
    if c.static:
        _run_static(name, c, args, got)
    if c.grad:
        _run_grad(name, c, args)


def test_executed_equals_served():
    """The ratchet: every served _C_ops name has a case (and parametrize
    above executes each); stale cases for names no longer served fail too."""
    served = set(_NAMES)
    cased = set(CASES)
    assert served - cased == set(), f"missing cases: {sorted(served - cased)}"
    assert cased - served == set(), f"stale cases: {sorted(cased - served)}"
