"""Chaos-hardened fleet (ISSUE 15): deterministic fault injection,
bounded RPC with retry/backoff, per-replica circuit breakers, and the
wedge watchdog.

Acceptance oracles:

1. NO HANG: under a seeded fault schedule (every fault kind, every
   named injection point) every client handle resolves — tokens or a
   typed ServingError — inside a global watchdog; surviving streams
   are token-identical to the fault-free oracle; drained fleets leak
   zero pages (tests the serving/disagg/chaos.py drill directly).
2. WEDGE WATCHDOG: a stalled-but-heartbeating replica (the engine
   loop wedged, the heartbeat thread alive) is detected, killed, and
   its in-flight work remigrated exactly like a crash.
3. BOUNDED RPC: every `_call` carries a deadline (ReplicaTimeoutError,
   never an unbounded wait); idempotent ops retry with backoff under
   a bounded attempt budget, non-idempotent ops fail fast.
4. CIRCUIT BREAKER: consecutive transport faults open it (the replica
   leaves every routing gate, all-open sheds typed), heartbeat
   recovery earns a single half-open probe, restart() backs off
   exponentially and refuses a crash loop.

The unit half runs in-process (socketpairs and bare transports — no
worker processes); the soak half reuses the dist_capability subprocess
probe and skips fast where fd-inheriting subprocesses are unavailable.
"""
import itertools
import random
import socket
import threading
import time

import pytest

from paddle_tpu import generation as gen
from paddle_tpu.generation.engine import GenerationHandle
from paddle_tpu.parallel import tp_mesh
from paddle_tpu.profiler.monitor import StatRegistry
from paddle_tpu.serving import fleet as fleet_mod
from paddle_tpu.serving.admission import (ReplicaTimeoutError,
                                          ServerBusyError, ServingError)
from paddle_tpu.serving.disagg.chaos import (chaos_drill,
                                             full_matrix_plans,
                                             kill_stall_plans)
from paddle_tpu.serving.disagg.faults import (FaultInjected, FaultPlan,
                                              FaultRule)
from paddle_tpu.serving.disagg.rpc import recv_frame, send_frame
from paddle_tpu.serving.disagg.transport import (RETRYABLE_OPS,
                                                 RpcPolicy,
                                                 SubprocTransport,
                                                 build_transport)
from paddle_tpu.serving.fleet import (CircuitBreaker, FleetConfig,
                                      FleetRouter, ReplicaSpec)

from dist_capability import (SUBPROC_SKIP_REASON,  # noqa: E402
                             subprocess_replicas_available)
from gen_oracle import greedy_oracle as _ref  # noqa: E402

needs_subproc = pytest.mark.skipif(
    not subprocess_replicas_available(), reason=SUBPROC_SKIP_REASON)

SYSTEM = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]   # 3 full pages @ ps=4


@pytest.fixture(autouse=True)
def _fresh_fleet_stats():
    reg = StatRegistry.instance()
    for name in list(reg.stats()):
        if name.startswith(fleet_mod.PREFIX):
            reg.get_stat(name).reset()
    yield


@pytest.fixture(scope="module")
def model():
    # same signature as the disagg/fleet/prefix suites: the
    # process-wide greedy_oracle memo shares reference streams
    return gen.TinyCausalLM(vocab_size=48, num_layers=2, num_heads=2,
                            head_dim=8, seed=3)


def _cfg(**kw):
    base = dict(max_decode_slots=4, num_pages=64, page_size=4,
                prefix_cache=True)
    base.update(kw)
    return gen.GenerationConfig(**base)


def _fleet(model, n=2, transport="inproc", cfgs=None, start=False,
           **fleet_kw):
    cfgs = cfgs or [_cfg() for _ in range(n)]
    specs = [ReplicaSpec(f"x{i}", model, c, transport=transport)
             for i, c in enumerate(cfgs)]
    return FleetRouter(specs, FleetConfig(start=start, seed=0,
                                          **fleet_kw))


def _stat(name):
    return StatRegistry.instance().get_stat(name).get()


class _Shell:
    """The minimal transport surface FaultPlan.on_send/on_recv touch."""

    def __init__(self, sock):
        self._sock = sock
        self._wlock = threading.Lock()
        self.killed = 0
        self.stalls = []

    def kill(self):
        self.killed += 1

    def _send_stall(self, stall_s):
        self.stalls.append(stall_s)

    def _send_plain(self, msg):
        send_frame(self._sock, msg, self._wlock)

    def _recv_plain(self):
        return recv_frame(self._sock)


def _bare_transport(sock, rpc=None, faults=None, reader=False):
    """A SubprocTransport shell over a raw socketpair — the RPC wait/
    retry/dispatch machinery without any worker process behind it."""
    t = SubprocTransport.__new__(SubprocTransport)
    t.name = "bare"
    t.registry = None
    t.engine = None
    t.on_death = None
    t.rpc = rpc or RpcPolicy(timeout_s=0.2, retries=3, backoff_s=0.01)
    t._faults = faults
    t._jitter = random.Random(0)
    t.timeout_total = 0
    t._sock = sock
    t._wlock = threading.Lock()
    t._lock = threading.Lock()
    t._ids = itertools.count(1)
    t._rpc_waits = {}
    t._inflight = {}
    t._deltas = []
    t._load = {"queue_depth": 0, "active": 0, "pages_in_use": 0,
               "num_pages": 1, "idle": True}
    t._last_hb = time.monotonic()
    t._progress_seq = None
    t._progress_at = time.monotonic()
    t._in_step = False
    t._idle_since = None
    t._dead = threading.Event()
    t._closing = False
    t._death_handled = False
    if reader:
        threading.Thread(target=t._read_loop, daemon=True).start()
    return t


# ---------------------------- typed errors -------------------------------


def test_replica_timeout_error_is_typed():
    """The new RPC-deadline error is a ServingError (the fleet's
    remigration ladder catches it) AND a TimeoutError (generic timeout
    handlers see it), distinct from the client-deadline error."""
    assert issubclass(ReplicaTimeoutError, ServingError)
    assert issubclass(ReplicaTimeoutError, TimeoutError)
    from paddle_tpu.serving.admission import DeadlineExceededError
    assert not issubclass(ReplicaTimeoutError, DeadlineExceededError)


def test_rpc_policy_validation():
    with pytest.raises(ValueError, match="timeout_s"):
        RpcPolicy(timeout_s=0)
    with pytest.raises(ValueError, match="retries"):
        RpcPolicy(retries=0)
    with pytest.raises(ValueError, match="backoff_s"):
        RpcPolicy(backoff_s=-1)
    assert "submit" not in RETRYABLE_OPS
    assert "import_seq" not in RETRYABLE_OPS
    assert {"stats", "load", "export_prefix"} <= RETRYABLE_OPS


# ---------------------------- fault plans --------------------------------


def test_fault_rule_validation():
    with pytest.raises(ValueError, match="kind"):
        FaultRule("submit", "meteor")
    with pytest.raises(ValueError, match="direction"):
        FaultRule("submit", "drop", direction="sideways")
    with pytest.raises(ValueError, match="count"):
        FaultRule("submit", "drop", count=0)


def test_fault_rule_deterministic_window():
    """A rule fires on exactly its [after, after+count) matching
    frames, counting ONLY frames that match its point/direction."""
    rule = FaultRule("submit", "drop", direction="send", after=1,
                     count=2)
    rng = random.Random(0)
    fires = [rule._matches("send", "submit", rng) for _ in range(5)]
    assert fires == [False, True, True, False, False]
    # non-matching frames do not advance the window
    rule2 = FaultRule("submit", "drop", after=1)
    assert rule2._matches("send", "stats", rng) is False
    assert rule2._matches("send", "submit", rng) is False   # 0th
    assert rule2._matches("send", "submit", rng) is True    # 1st


def test_fault_plan_seeded_prob_reproducible():
    """Probabilistic rules draw from the plan's seeded RNG: two plans
    with the same seed fire on the same frames."""
    def run(seed):
        plan = FaultPlan([FaultRule("any", "drop", prob=0.5)],
                         seed=seed)
        return [bool(plan._take("send", "submit")) for _ in range(20)]

    assert run(3) == run(3)
    assert run(3) != run(4)


def test_fault_plan_disarmed_passthrough():
    """A disarmed plan matches nothing and counts nothing — the drill
    warms its fleet up before the schedule starts ticking."""
    plan = FaultPlan([FaultRule("submit", "drop", after=0, count=99)],
                     armed=False)
    assert plan._take("send", "submit") == []
    plan.arm()
    assert len(plan._take("send", "submit")) == 1
    assert plan.fired_kinds() == ["drop"]


def test_faulty_send_drop_dup_delay():
    """Send-side drop (peer sees nothing), dup (peer sees it twice),
    delay (the write is held) through the real codec."""
    a, b = socket.socketpair()
    b.settimeout(2.0)
    shell = _Shell(a)
    plan = FaultPlan([FaultRule("submit", "drop", after=0),
                      FaultRule("stats", "dup", after=0),
                      FaultRule("load", "delay", after=0,
                                delay_s=0.15)])
    plan.on_send(shell, {"op": "submit", "rid": 1})   # dropped
    plan.on_send(shell, {"op": "stats", "rid": 2})    # duplicated
    t0 = time.monotonic()
    plan.on_send(shell, {"op": "load", "rid": 3})     # delayed
    assert time.monotonic() - t0 >= 0.15
    got = [recv_frame(b) for _ in range(3)]
    assert [g["op"] for g in got] == ["stats", "stats", "load"]
    a.close()
    b.close()


def test_faulty_send_corrupt_and_truncate_poison_peer():
    """Corrupt: the peer's unpickle dies (a crashed worker — EOF is
    the detection).  Truncate: the peer blocks mid-frame, the torn-
    write wedge that only RPC deadlines catch."""
    a, b = socket.socketpair()
    b.settimeout(1.0)
    shell = _Shell(a)
    plan = FaultPlan([FaultRule("submit", "corrupt", after=0)], seed=1)
    plan.on_send(shell, {"op": "submit", "rid": 1, "payload": [1] * 64})
    with pytest.raises(Exception):   # noqa: B017 — any unpickle error
        recv_frame(b)
    a2, b2 = socket.socketpair()
    b2.settimeout(0.3)
    shell2 = _Shell(a2)
    plan2 = FaultPlan([FaultRule("submit", "truncate", after=0)])
    plan2.on_send(shell2, {"op": "submit", "rid": 1,
                           "payload": [2] * 64})
    with pytest.raises(socket.timeout):   # blocked mid-frame forever
        recv_frame(b2)
    for s in (a, a2, b2):
        s.close()


def test_faulty_recv_drop_dup_corrupt_kill_stall():
    """Recv-side faults through on_recv: drop returns no frames, dup
    returns two, corrupt raises the typed poison, kill/stall call the
    transport hooks."""
    a, b = socket.socketpair()
    shell = _Shell(b)
    plan = FaultPlan([FaultRule("token", "drop", after=0),
                      FaultRule("token", "dup", after=1),
                      FaultRule("done", "kill", after=0),
                      FaultRule("hb", "stall", after=0, stall_s=7.5),
                      FaultRule("resp", "corrupt", after=0)])
    frames = [{"ev": "token", "sid": 1, "t": 5, "n": 0},
              {"ev": "token", "sid": 1, "t": 6, "n": 1},
              {"ev": "done", "sid": 1, "result": {}},
              {"ev": "hb", "load": {}},
              {"resp": 9, "ok": True}]
    for f in frames:
        send_frame(a, f)
    assert plan.on_recv(shell) == []                      # dropped
    assert [f["t"] for f in plan.on_recv(shell)] == [6, 6]  # dup
    assert plan.on_recv(shell)[0]["ev"] == "done"         # + kill
    assert shell.killed == 1
    assert plan.on_recv(shell)[0]["ev"] == "hb"           # + stall
    assert shell.stalls == [7.5]
    with pytest.raises(FaultInjected):
        plan.on_recv(shell)
    a.close()
    b.close()


def test_full_matrix_plans_cover_kinds_and_spare_is_safe():
    """The drill's default schedule names every kind, and the spare
    replica carries no fatal rules (survivors need a home)."""
    plans = full_matrix_plans(5, ["a", "b", "c"])
    from paddle_tpu.serving.disagg.faults import FATAL_KINDS
    all_kinds = {r.kind for p in plans.values() for r in p.rules}
    assert all_kinds == {"drop", "delay", "dup", "corrupt",
                         "truncate", "kill", "stall"}
    assert not any(r.kind in FATAL_KINDS for r in plans["a"].rules)
    with pytest.raises(ValueError, match="2 replicas"):
        full_matrix_plans(0, ["solo"])
    # seeded: same seed, same schedule
    again = full_matrix_plans(5, ["a", "b", "c"])
    assert [(r.point, r.kind, r.after) for p in plans.values()
            for r in p.rules] == \
        [(r.point, r.kind, r.after) for p in again.values()
         for r in p.rules]


# --------------------------- bounded RPC ---------------------------------


def test_call_default_deadline_bounded():
    """_call with timeout=None uses the POLICY deadline — never
    unbounded — and a miss is the typed ReplicaTimeoutError."""
    a, b = socket.socketpair()
    t = _bare_transport(a, rpc=RpcPolicy(timeout_s=0.15, retries=1))
    t0 = time.monotonic()
    with pytest.raises(ReplicaTimeoutError, match="deadline"):
        t._call({"op": "stats"})
    assert 0.1 < time.monotonic() - t0 < 2.0
    assert t.timeout_total == 1
    assert t._rpc_waits == {}   # the wait slot was reclaimed
    a.close()
    b.close()


def test_idempotent_retry_succeeds_on_late_attempt():
    """An idempotent op retries under the bounded attempt budget with
    backoff; the peer answering only the 3rd attempt still succeeds."""
    a, b = socket.socketpair()
    t = _bare_transport(a, rpc=RpcPolicy(timeout_s=0.15, retries=3,
                                         backoff_s=0.01), reader=True)
    seen = []

    def peer():
        while len(seen) < 3:
            frame = recv_frame(b)
            seen.append(frame)
            if len(seen) == 3:
                send_frame(b, {"resp": frame["rid"], "ok": {"n": 42}})

    th = threading.Thread(target=peer, daemon=True)
    th.start()
    assert t._call_idempotent({"op": "stats"}) == {"n": 42}
    assert len(seen) == 3 and t.timeout_total == 2
    a.close()
    b.close()


def test_non_idempotent_fails_fast_single_attempt():
    """submit/import_seq never retry: one attempt, one typed error —
    the remigration ladder owns recovery (a lost reply may mean the op
    LANDED; re-issuing would double-run it)."""
    a, b = socket.socketpair()
    b.settimeout(1.0)
    t = _bare_transport(a, rpc=RpcPolicy(timeout_s=0.1, retries=3,
                                         backoff_s=0.01))
    with pytest.raises(ReplicaTimeoutError):
        t._call({"op": "submit", "sid": 1, "prompt": [], "kwargs": {}})
    assert recv_frame(b)["op"] == "submit"
    b.settimeout(0.2)
    with pytest.raises(socket.timeout):   # no second attempt on wire
        recv_frame(b)
    with pytest.raises(AssertionError):   # and the API refuses it
        t._call_idempotent({"op": "submit"})
    a.close()
    b.close()


def test_every_drain_call_site_is_bounded():
    """Satellite audit regression: no `_call` site may pass an
    unbounded deadline — drain's longer budget is explicit, shutdown
    is clamped, and the module never waits on `ev.wait()` bare."""
    import inspect

    from paddle_tpu.serving.disagg import transport as tr
    src = inspect.getsource(tr)
    assert "ev.wait()" not in src
    # drain opts into timeout + policy — the one allowed longer budget
    assert "float(timeout) + self.rpc.timeout_s" in src


# ------------------------ ordered stream protocol ------------------------


def _entry(handle, base=0):
    return {"prompt": [1], "kwargs": {}, "handle": handle,
            "emitted": base, "base": base, "next": 0, "ahead": {},
            "last_event": time.monotonic(), "deadline": None}


def test_stream_protocol_dedup_reorder_and_backfill():
    """Token events carry per-stream indexes: duplicated frames are
    dropped, an early frame is HELD until its predecessors arrive, and
    a lost frame is backfilled from the authoritative result at done —
    the client always sees the exact token sequence, in order."""
    a, _b = socket.socketpair()
    t = _bare_transport(a)
    h = GenerationHandle()
    t._inflight[7] = _entry(h)
    t._dispatch({"ev": "token", "sid": 7, "t": 10, "n": 0})
    t._dispatch({"ev": "token", "sid": 7, "t": 10, "n": 0})   # dup
    t._dispatch({"ev": "token", "sid": 7, "t": 12, "n": 2})   # early
    assert t._inflight[7]["next"] == 1   # 12 held, not delivered
    t._dispatch({"ev": "token", "sid": 7, "t": 11, "n": 1})   # fills
    assert t._inflight[7]["next"] == 3   # 11 then buffered 12 flushed
    # token n=3 LOST; done backfills it from the result
    t._dispatch({"ev": "done", "sid": 7, "prefix_hit": None,
                 "result": {"token_ids": [10, 11, 12, 13],
                            "finish_reason": "length",
                            "prompt_len": 1, "preemptions": 0}})
    assert h.result(timeout=1).token_ids == [10, 11, 12, 13]
    assert list(h.tokens(timeout=1)) == [10, 11, 12, 13]
    assert h.n_streamed == 4
    a.close()
    _b.close()


def test_stream_backfill_respects_migration_base():
    """An import-seated stream (live migration) backfills only PAST
    its base: the client already holds the pre-migration prefix."""
    a, _b = socket.socketpair()
    t = _bare_transport(a)
    h = GenerationHandle()
    for tok in (20, 21, 22):
        h._push_token(tok)   # streamed before the migration
    t._inflight[3] = _entry(h, base=3)
    t._dispatch({"ev": "token", "sid": 3, "t": 23, "n": 0})
    t._dispatch({"ev": "done", "sid": 3, "prefix_hit": None,
                 "result": {"token_ids": [20, 21, 22, 23, 24],
                            "finish_reason": "length",
                            "prompt_len": 1, "preemptions": 0}})
    assert list(h.tokens(timeout=1)) == [20, 21, 22, 23, 24]
    assert h.n_streamed == 5   # nothing re-pushed, one backfilled
    a.close()
    _b.close()


# ------------------------- wedge / orphan logic --------------------------


def test_wedged_soft_and_hard_clocks():
    """Soft clock: busy + frozen progress + NOT inside a step.  An
    engine mid-step (long jit compile) is protected until the hard
    ceiling."""
    a, _b = socket.socketpair()
    t = _bare_transport(a)
    t._load = dict(t._load, idle=False)
    t._progress_at = time.monotonic() - 3.0
    assert t.wedged(2.0)
    assert not t.wedged(5.0)            # not frozen long enough
    t._in_step = True
    assert not t.wedged(2.0)            # compiling is progress
    assert t.wedged(2.0, hard_after_s=2.5)   # ... up to the ceiling
    t._in_step = False
    t._load = dict(t._load, idle=True)
    assert not t.wedged(0.1)            # idle is never wedged
    t._dead.set()
    assert not t.wedged(0.1)
    a.close()
    _b.close()


def test_take_orphans_requires_idle_worker_and_stale_entry():
    """The orphan sweep only claims entries when the worker has
    reported idle past the grace AND the entry saw no event for the
    grace — a busy worker or a fresh submit is never stolen."""
    a, _b = socket.socketpair()
    t = _bare_transport(a)
    h = GenerationHandle()
    entry = _entry(h)
    entry["last_event"] = time.monotonic() - 5.0
    t._inflight[1] = entry
    assert t.take_orphans(2.0) == []        # worker not idle
    t._idle_since = time.monotonic() - 3.0
    fresh = _entry(GenerationHandle())      # just submitted
    t._inflight[2] = fresh
    orphans = t.take_orphans(2.0)
    assert orphans == [entry]               # stale one only
    assert list(t._inflight) == [2]
    a.close()
    _b.close()


# --------------------------- circuit breaker -----------------------------


def test_circuit_breaker_state_machine():
    opened = []
    b = CircuitBreaker(threshold=2, cooldown_s=0.05,
                       on_open=lambda: opened.append(1))
    assert b.state == "closed" and b.routable() and b.admit()
    b.record_failure()
    assert b.state == "closed"          # below threshold
    b.record_failure()
    assert b.state == "open" and opened == [1]
    assert not b.routable(hb_age=0.0)   # cooldown not elapsed
    time.sleep(0.06)
    assert not b.routable(hb_age=99.0)  # no heartbeat recovery
    assert b.routable(hb_age=0.0)
    assert b.admit(hb_age=0.0)          # claims THE half-open probe
    assert b.state == "half-open"
    assert not b.admit(hb_age=0.0)      # second probe refused
    b.record_failure()                  # probe failed -> reopen
    assert b.state == "open" and opened == [1, 1]
    time.sleep(0.06)
    assert b.admit(hb_age=0.0)
    b.record_success()
    assert b.state == "closed" and b.failures == 0
    with pytest.raises(ValueError, match="threshold"):
        CircuitBreaker(threshold=0)


def test_breaker_busy_releases_probe_without_fault():
    """ServerBusyError is back-pressure, not breakage: it releases a
    claimed half-open probe and never counts toward the threshold."""
    b = CircuitBreaker(threshold=2, cooldown_s=0.0)
    b.record_failure()
    b.record_failure()
    assert b.state == "open"
    assert b.admit(hb_age=0.0)
    b.record_busy()                     # busy probe: state unchanged,
    assert b.state == "half-open"       # slot released
    assert b.admit(hb_age=0.0)
    b.record_success()
    assert b.state == "closed"
    for _ in range(100):
        b.record_busy()
    assert b.state == "closed" and b.failures == 0


def test_breaker_gates_routing_and_all_open_sheds_typed(model):
    """An open breaker takes its replica out of every routing gate;
    every breaker open is the typed fleet shed; a healthy sibling
    keeps serving."""
    fl = _fleet(model, breaker_threshold=1, breaker_cooldown_s=30.0)
    victim = fl._replicas["x0"]
    victim.breaker.record_failure()
    assert victim.breaker.state == "open"
    h = fl.submit(SYSTEM, max_new_tokens=2)
    fl.run_until_idle()
    h.result(timeout=5)
    snap = fl.stats_snapshot()
    assert snap["replicas"]["x0"]["generation"] \
        .get("generation.requests_total", 0) == 0
    assert snap["replicas"]["x0"]["breaker"] == "open"
    assert snap["fleet"][fleet_mod.BREAKER_STATE + ".x0"] == 2
    fl._replicas["x1"].breaker.record_failure()
    with pytest.raises(ServerBusyError, match="circuit breaker"):
        fl.submit(SYSTEM, max_new_tokens=2)
    assert _stat(fleet_mod.SHED_TOTAL) == 1
    assert _stat(fleet_mod.BREAKER_OPEN_TOTAL) == 2
    fl.shutdown()


def test_breaker_half_open_probe_recovers_inproc(model):
    """After the cooldown (inproc heartbeats are always fresh) ONE
    probe request flows; its success closes the breaker for good."""
    fl = _fleet(model, n=1, breaker_threshold=1,
                breaker_cooldown_s=0.02)
    rep = fl._replicas["x0"]
    rep.breaker.record_failure()
    assert rep.breaker.state == "open"
    with pytest.raises(ServerBusyError):
        fl.submit(SYSTEM, max_new_tokens=2)   # still cooling down
    time.sleep(0.03)
    h = fl.submit(SYSTEM, max_new_tokens=2)   # the half-open probe
    assert rep.breaker.state == "closed"      # submit ack == success
    fl.run_until_idle()
    assert h.result(timeout=5).token_ids == _ref(model, SYSTEM, 2)
    fl.shutdown()


# ------------------------ respawn backoff / crash loop -------------------


def test_restart_backoff_exponential_cap_and_crash_loop(model):
    fl = _fleet(model, respawn_backoff_s=0.05, respawn_backoff_cap_s=0.2,
                max_respawns=3, respawn_reset_s=1000.0)
    rep = fl._replicas["x0"]

    def die():
        rep.state = "serving"
        fl._handle_death(rep.transport)
        assert rep.state == "dead"

    die()
    assert rep.respawns == 1
    rep.died_at = time.monotonic()   # backoff measured from death
    with pytest.raises(ServingError, match="backoff"):
        fl.restart("x0", wait=False)
    t0 = time.monotonic()
    fl.restart("x0", wait=True)      # sleeps the ~0.05s remainder
    assert time.monotonic() - t0 >= 0.02
    assert rep.state == "serving"
    # streak grows the backoff exponentially, capped
    die()
    assert rep.respawns == 2
    assert _stat(fleet_mod.REPLICA_DEAD_TOTAL) == 2
    fl.restart("x0", wait=True)
    die()
    die_backoff = min(0.2, 0.05 * 2 ** 2)
    fl.restart("x0", wait=True)
    assert _stat(fleet_mod.RESPAWN_BACKOFF_S + ".x0") == die_backoff
    die()
    with pytest.raises(ServingError, match="crash-looping"):
        fl.restart("x0")             # respawns=4 > max_respawns=3
    assert rep.state == "dead"
    fl.reset_respawn("x0")
    assert rep.respawns == 0
    fl.restart("x0", wait=True)
    assert rep.state == "serving"
    fl.shutdown()


def test_clean_drain_owes_no_backoff(model):
    fl = _fleet(model, respawn_backoff_s=60.0)
    fl._replicas["x0"].respawns = 2   # residue from earlier crashes
    fl.drain("x0")
    assert fl._replicas["x0"].respawns == 0
    t0 = time.monotonic()
    fl.restart("x0", wait=True)       # instant: no backoff owed
    assert time.monotonic() - t0 < 1.0
    fl.shutdown()


# ----------------------------- config / metrics --------------------------


def test_fleet_config_validation_new_knobs():
    with pytest.raises(ValueError, match="timeout_s"):
        FleetConfig(rpc_timeout_s=0)
    with pytest.raises(ValueError, match="retries"):
        FleetConfig(rpc_retries=0)
    with pytest.raises(ValueError, match="breaker_threshold"):
        FleetConfig(breaker_threshold=0)
    with pytest.raises(ValueError, match="wedge_after_s"):
        FleetConfig(wedge_after_s=0)
    with pytest.raises(ValueError, match="wedge_hard_after_s"):
        FleetConfig(wedge_hard_after_s=-1)
    with pytest.raises(ValueError, match="max_respawns"):
        FleetConfig(max_respawns=0)
    with pytest.raises(ValueError, match="watchdog_interval_s"):
        FleetConfig(watchdog_interval_s=0)
    assert FleetConfig(wedge_hard_after_s=None).wedge_hard_after_s \
        is None


def test_fault_plan_config_plumbing(model):
    """fault_plans must name known replicas and require the proc
    transport — an inproc replica has no wire to fault."""
    with pytest.raises(ValueError, match="unknown replicas"):
        _fleet(model, fault_plans={"ghost": FaultPlan([])})
    with pytest.raises(ValueError, match="no wire"):
        _fleet(model, fault_plans={"x0": FaultPlan([])})
    with pytest.raises(ValueError, match="no wire"):
        build_transport(ReplicaSpec("i", model, _cfg()), "inproc",
                        fault_plan=FaultPlan([]))


def test_robustness_metrics_schema_complete_and_zeroed_inproc(model):
    """The new fleet.* keys are all present from the FIRST snapshot,
    zeroed for an all-inproc fleet (no RPC, no faults)."""
    fl = _fleet(model)
    snap = fl.stats_snapshot()
    fsnap = snap["fleet"]
    for key in (fleet_mod.BREAKER_OPEN_TOTAL, fleet_mod.BREAKER_STATE,
                fleet_mod.REPLICA_TIMEOUT_TOTAL,
                fleet_mod.WEDGE_KILL_TOTAL,
                fleet_mod.ORPHAN_REMIGRATED_TOTAL,
                fleet_mod.RESPAWN_BACKOFF_S):
        assert key in fsnap, key
        assert fsnap[key] == 0
    for name in ("x0", "x1"):
        rep = snap["replicas"][name]
        assert rep["breaker"] == "closed"
        assert rep["respawns"] == 0
        assert rep["rpc_timeouts"] == 0
    fl.shutdown()


# ---------------------- adoption outside the lock ------------------------


def test_adoption_runs_outside_routing_lock_and_degrades_typed(model):
    """The satellite: the page-transfer RPCs run OUTSIDE the routing
    lock, and a timed-out holder degrades the request to the
    cold-prefill ladder — typed, counted, admission never stalled.
    Pinned to the relay wire + synchronous adoption: the fault hooks
    the router-side export_prefix RPC, and the sync path is the one
    whose transfer could ever sit on the request's critical path (the
    async scheduler has its own chaos suite in test_data_plane.py)."""
    fl = _fleet(model, page_transfer="relay", async_adoption=False)
    h1 = fl.submit(SYSTEM + [7], max_new_tokens=4)
    fl.run_until_idle()
    h1.result(timeout=5)
    counts = {n: r.get("generation", {})
              .get("generation.requests_total", 0)
              for n, r in fl.stats_snapshot()["replicas"].items()}
    holder = max(counts, key=counts.get)
    other = next(n for n in fl._replicas if n != holder)
    lock_held = []

    def boom(tokens):
        lock_held.append(fl._lock.locked())
        raise ReplicaTimeoutError("export deadline (chaos)")

    fl._replicas[holder].transport.export_prefix = boom
    fl._sessions["pin"] = other
    h2 = fl.submit(SYSTEM + [9, 9], max_new_tokens=4, session="pin")
    fl.run_until_idle()
    assert h2.result(timeout=5).token_ids == \
        _ref(model, SYSTEM + [9, 9], 4)
    assert lock_held == [False]   # byte transfer outside the lock
    assert h2.prefix_hit_tokens == 0          # served cold, not hung
    assert _stat(fleet_mod.REPLICA_TIMEOUT_TOTAL) == 1
    assert _stat(fleet_mod.PAGE_ADOPTIONS) == 0
    assert fl._replicas[holder].breaker.failures == 1
    fl.shutdown()


# ------------------- crash-during-import consistency ---------------------


@pytest.mark.parametrize("seam", ["adopt", "place"])
@pytest.mark.parametrize("layout,kv_dtype", [
    ("token", None), ("token", "int8"), ("kernel", "int8")])
def test_import_failure_leaves_pools_consistent(model, layout,
                                                kv_dtype, seam):
    """Satellite: a failure injected mid-`import_sequence` (the
    surviving half of a crash-during-import) leaves the importer
    refusing TYPED (False -> cold ladder) with ZERO leaked pages and
    the engine still able to adopt for real — across layouts x int8,
    whether the install died BEFORE the pages attached to a sequence
    (`adopt`) or after (`place`)."""
    kw = dict(kv_backend="device", pool_layout=layout)
    if kv_dtype:
        kw["kv_dtype"] = kv_dtype
    a = gen.GenerationEngine(model, _cfg(**kw), start=False)
    h = a.submit(SYSTEM + [7, 7], max_new_tokens=8)
    for _ in range(4):
        a.step()
    _, live = a.evacuate_for_migration()
    snap = live[0]
    b = gen.GenerationEngine(model, _cfg(**kw), start=False)
    target = (b.cache if seam == "adopt" else b.scheduler)
    attr = "adopt_imported" if seam == "adopt" else "place_imported"
    orig = getattr(target, attr)
    calls = []

    def boom(*args, **kwargs):
        # fail the FIRST install only: the recovery rollback (which
        # reuses cache plumbing) must run clean, exactly as it would
        # when the fault was a poisoned snapshot, not a dead pool
        if not calls:
            calls.append(1)
            raise RuntimeError("chaos: killed mid-install")
        return orig(*args, **kwargs)

    setattr(target, attr, boom)
    assert b.import_sequence(dict(snap)) is False
    assert calls and b.cache.pages_in_use == 0   # nothing leaked
    setattr(target, attr, orig)
    # the pool was not poisoned: the real import adopts and RESUMES
    assert b.import_sequence(snap) is True
    b.run_until_idle()
    assert h.result(timeout=5).token_ids == \
        _ref(model, SYSTEM + [7, 7], 8)
    b.cache.flush_prefix_cache()
    assert b.cache.pages_in_use == 0
    a.shutdown()
    b.shutdown()


def test_import_failure_consistent_on_mesh():
    """The 4-dev CPU mesh cell of the same satellite: the donated
    sharded import path rolls back cleanly too."""
    model4 = gen.TinyCausalLM(vocab_size=32, num_layers=2, num_heads=4,
                              head_dim=8, seed=5)
    mesh = tp_mesh(4)
    kw = dict(kv_backend="device", mesh=mesh)
    a = gen.GenerationEngine(model4, _cfg(**kw), start=False)
    h = a.submit(SYSTEM + [2], max_new_tokens=6)
    for _ in range(4):
        a.step()
    _, live = a.evacuate_for_migration()
    snap = live[0]
    b = gen.GenerationEngine(model4, _cfg(**kw), start=False)
    orig = b.cache.adopt_imported
    calls = []

    def boom(*args, **kwargs):
        if not calls:
            calls.append(1)
            raise RuntimeError("chaos: killed mid-install")
        return orig(*args, **kwargs)

    b.cache.adopt_imported = boom
    assert b.import_sequence(dict(snap)) is False
    assert b.cache.pages_in_use == 0
    b.cache.adopt_imported = orig
    assert b.import_sequence(snap) is True
    b.run_until_idle()
    assert h.result(timeout=5).token_ids == \
        _ref(model4, SYSTEM + [2], 6)
    a.shutdown()
    b.shutdown()


# --------------------------- chaos soak drills ---------------------------


@pytest.mark.slow   # the full kind x point matrix over a 3-child
# subprocess fleet runs ~36s on one core (conftest slow-lane
# convention); the kill+stall schedule drill below keeps a seeded
# multi-fault soak in tier-1
@needs_subproc
def test_chaos_drill_full_matrix_deterministic(model):
    """THE acceptance soak: the seeded full kind x point matrix over a
    3-replica subprocess fleet — no stream hangs, survivors are
    token-identical to the fault-free oracle, zero pages leak.  The
    assertions live INSIDE chaos_drill; the report's fired log proves
    the schedule actually exercised the faults."""
    report = chaos_drill(model, seed=11, n_replicas=3, n_requests=8,
                         new_tokens=8, watchdog_s=120.0,
                         restart_dead=True)
    assert report["hung"] == 0
    assert report["leaked_pages"] == 0
    assert report["resolved_ok"] + report["resolved_typed_error"] == 8
    assert report["token_identical"] == report["resolved_ok"]
    fired = {k for kinds in report["faults_fired"].values()
             for k in kinds}
    assert fired   # the schedule genuinely ran faults into the fleet


@needs_subproc
def test_chaos_drill_kill_and_stall_schedule(model):
    """The gen_bench --chaos schedule: a mid-stream SIGKILL plus a
    stalled-but-heartbeating engine.  The wedge watchdog converts the
    stall into a death (wedge_kill_total), remigration keeps every
    stream intact, and the books balance."""
    plans = kill_stall_plans(7, ["c0", "c1", "c2"])
    report = chaos_drill(model, seed=7, n_replicas=3, n_requests=6,
                         new_tokens=8, plans=plans, watchdog_s=120.0)
    assert report["hung"] == 0 and report["leaked_pages"] == 0
    assert report["resolved_ok"] + report["resolved_typed_error"] == 6
    assert report["token_identical"] == report["resolved_ok"]
    assert report["wedge_kill_total"] >= 1       # the stall was CAUGHT
    assert report["replica_dead_total"] >= 1
    assert "stall" in {k for ks in report["faults_fired"].values()
                       for k in ks}


@pytest.mark.slow   # two ~35s subprocess-fleet soaks (conftest
# slow-lane convention); int8 pool + layout coverage stays in tier-1
# via test_kv_quant / test_fused_decode, fault coverage via the drills
# above
@needs_subproc
@pytest.mark.parametrize("layout", ["token", "kernel"])
def test_chaos_drill_int8_pools(model, layout):
    """Acceptance sweep: the drill holds across both device pool
    layouts x int8 — scale payloads ride every remigration and the
    quantized pools leak nothing under kill + stall."""
    plans = kill_stall_plans(3, ["c0", "c1"])
    report = chaos_drill(
        model, seed=3, n_replicas=2, n_requests=4, new_tokens=6,
        plans=plans, watchdog_s=120.0,
        engine_kw=dict(kv_backend="device", pool_layout=layout,
                       kv_dtype="int8"))
    assert report["hung"] == 0 and report["leaked_pages"] == 0
    assert report["resolved_ok"] + report["resolved_typed_error"] == 4
    assert report["token_identical"] == report["resolved_ok"]


@needs_subproc
def test_dropped_done_event_orphan_remigrated(model):
    """A lost completion event (drop the `done` frame) leaves a
    lingering ledger entry on an idle worker: the watchdog's orphan
    sweep remigrates it — the stream resolves token-identical instead
    of hanging forever."""
    plan = FaultPlan([FaultRule("done", "drop", direction="recv",
                                after=0)])
    report = chaos_drill(model, seed=5, n_replicas=2, n_requests=3,
                         new_tokens=6, plans={"c1": plan},
                         watchdog_s=120.0)
    assert report["hung"] == 0
    assert report["resolved_ok"] == 3 == report["token_identical"]
    if "drop" in {k for ks in report["faults_fired"].values()
                  for k in ks}:
        assert report["orphan_remigrated_total"] >= 1


@needs_subproc
def test_rpc_timeouts_open_breaker_then_recover(model):
    """Dropped submit frames time out typed (bounded RPC), consecutive
    timeouts OPEN the replica's breaker (it leaves the routing gates),
    and after the schedule drains + cooldown a half-open probe brings
    it back — no stream ever hangs on the way."""
    specs = [ReplicaSpec(f"c{i}", model, _cfg()) for i in range(2)]
    plan = FaultPlan([FaultRule("submit", "drop", direction="send",
                                after=0, count=2)])
    fl = FleetRouter(specs, FleetConfig(
        seed=0, transport="proc", rpc_timeout_s=0.4, rpc_retries=2,
        breaker_threshold=2, breaker_cooldown_s=0.3,
        # quiesce the background sweep: its ping probe would heal the
        # open breaker autonomously (that path has its own tests in
        # test_control_plane) and race the mid-state assert below —
        # THIS test pins the client-driven half-open probe
        watchdog_interval_s=3600.0,
        fault_plans={"c1": plan}))
    try:
        victim = fl._replicas["c1"]
        for i in range(2):
            fl._sessions[f"s{i}"] = "c1"
            h = fl.submit(SYSTEM + [i], max_new_tokens=4,
                          session=f"s{i}")
            # the pinned submit timed out, the ladder placed it on c0
            assert h.result(timeout=60).token_ids == \
                _ref(model, SYSTEM + [i], 4)
        assert victim.breaker.state == "open"
        assert _stat(fleet_mod.BREAKER_OPEN_TOTAL) == 1
        assert _stat(fleet_mod.REPLICA_TIMEOUT_TOTAL) >= 2
        time.sleep(0.4)   # cooldown; heartbeats kept flowing
        fl._sessions["s9"] = "c1"
        h = fl.submit(SYSTEM + [9], max_new_tokens=4, session="s9")
        assert h.result(timeout=60).token_ids == \
            _ref(model, SYSTEM + [9], 4)
        assert victim.breaker.state == "closed"   # probe succeeded
    finally:
        fl.shutdown()


@needs_subproc
def test_kill_during_export_degrades_adoption_cold(model):
    """Satellite (crash-during-export): the holder dies the instant
    the router asks it to export a warm run — the adoption degrades
    typed, the request completes COLD and token-identical on the
    chosen replica, and the death is handled like any crash."""
    specs = [ReplicaSpec(f"c{i}", model, _cfg()) for i in range(2)]
    plan = FaultPlan([FaultRule("export_prefix", "kill",
                                direction="send", after=0)])
    fl = FleetRouter(specs, FleetConfig(seed=0, transport="proc",
                                        rpc_timeout_s=5.0,
                                        fault_plans={"c0": plan},
                                        heartbeat_dead_after=10.0,
                                        page_transfer="relay",
                                        async_adoption=False))
    try:
        fl._sessions["seed"] = "c0"
        h1 = fl.submit(SYSTEM + [7], max_new_tokens=4, session="seed")
        h1.result(timeout=60)
        # wait for c0's registration deltas to reach the fleet index
        deadline = time.monotonic() + 15
        while fl._page_index.lookup(SYSTEM + [9], 4) is None \
                and time.monotonic() < deadline:
            fl.stats_snapshot()
            time.sleep(0.05)
        assert fl._page_index.lookup(SYSTEM + [9], 4) is not None
        fl._sessions["pin"] = "c1"
        h2 = fl.submit(SYSTEM + [9], max_new_tokens=4, session="pin")
        assert h2.result(timeout=60).token_ids == \
            _ref(model, SYSTEM + [9], 4)
        assert h2.prefix_hit_tokens == 0     # cold: the export died
        assert _stat(fleet_mod.PAGE_ADOPTIONS) == 0
        deadline = time.monotonic() + 15
        while fl._replicas["c0"].state != "dead" \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert fl._replicas["c0"].state == "dead"
    finally:
        fl.shutdown()
