"""Orbax sharded checkpoint of CompiledTrainStep state: save a ZeRO-3
dp x tp run mid-training, clobber the state, restore, and the loss
trajectory continues identically — shards restored onto their devices.
"""
import numpy as np

import paddle_tpu as paddle


def test_zero3_save_restore_roundtrip(tmp_path):
    import jax.numpy as jnp

    from paddle_tpu.models.gpt import GPTForPretraining, GPTConfig
    from paddle_tpu.parallel.env import build_mesh
    from paddle_tpu.parallel.hybrid import CompiledTrainStep
    from paddle_tpu.io.sharded_ckpt import save_train_state, load_train_state

    kw = dict(vocab_size=256, hidden_size=32, num_layers=2, num_heads=2,
              max_seq_len=32, dropout=0.0)
    paddle.seed(11)
    model = GPTForPretraining(GPTConfig(**kw))
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    mesh = build_mesh({"data": 4, "model": 2})
    tr = CompiledTrainStep(model, lambda m, i, l: m.loss(i, l), opt, mesh,
                           zero_stage=3)
    ids = paddle.to_tensor(np.random.RandomState(5).randint(
        0, 256, (8, 16)).astype(np.int32))

    for _ in range(2):
        tr.step(ids, ids)
    save_train_state(tr, str(tmp_path / "ckpt"))
    want = [float(np.asarray(tr.step(ids, ids)._data)) for _ in range(2)]

    # clobber: re-run two extra steps so params/opt drift, then restore
    for _ in range(2):
        tr.step(ids, ids)
    load_train_state(tr, str(tmp_path / "ckpt"))
    got = [float(np.asarray(tr.step(ids, ids)._data)) for _ in range(2)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_lr_scheduler_and_rng_restored(tmp_path):
    """Resume must continue the LR schedule (not restart warm-up) and the
    rng stream: a decayed-LR run saved at step 2 and restored later keeps
    the step-2 scheduler state."""
    from paddle_tpu.models.gpt import GPTForPretraining, GPTConfig
    from paddle_tpu.parallel.env import build_mesh
    from paddle_tpu.parallel.hybrid import CompiledTrainStep
    from paddle_tpu.io.sharded_ckpt import save_train_state, load_train_state

    paddle.seed(23)
    model = GPTForPretraining(GPTConfig(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=32, dropout=0.0))
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=1,
                                          gamma=0.5)
    opt = paddle.optimizer.SGD(learning_rate=sched,
                               parameters=model.parameters())
    tr = CompiledTrainStep(model, lambda m, i, l: m.loss(i, l), opt,
                           build_mesh({"data": 2}))
    ids = paddle.to_tensor(np.random.RandomState(9).randint(
        0, 128, (4, 16)).astype(np.int32))
    tr.step(ids, ids)
    tr.step(ids, ids)
    lr_at_save = opt.get_lr()
    save_train_state(tr, str(tmp_path / "ck"))
    tr.step(ids, ids)
    assert opt.get_lr() < lr_at_save  # schedule advanced past the save
    load_train_state(tr, str(tmp_path / "ck"))
    np.testing.assert_allclose(opt.get_lr(), lr_at_save, rtol=1e-9)
    assert tr._step_count == 2


def test_pipeline_trainer_roundtrip(tmp_path):
    """PipelinedTrainStep state (other/block params + grouped opt state)
    saves and restores through the same API."""
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny
    from paddle_tpu.parallel.env import build_mesh
    from paddle_tpu.parallel.pipeline_compile import (
        PipelinedTrainStep, GPTPipeAdapter,
    )
    from paddle_tpu.io.sharded_ckpt import save_train_state, load_train_state

    paddle.seed(31)
    model = GPTForPretraining(gpt_tiny())
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    tr = PipelinedTrainStep(GPTPipeAdapter(model), opt,
                            build_mesh({"pipe": 2, "data": 2}), num_micro=2)
    ids = paddle.to_tensor(np.random.RandomState(4).randint(
        0, model.config.vocab_size, (4, 16)).astype(np.int32))
    tr.step(ids, ids)
    save_train_state(tr, str(tmp_path / "ck"))
    want = float(np.asarray(tr.step(ids, ids)._data))
    tr.step(ids, ids)
    load_train_state(tr, str(tmp_path / "ck"))
    got = float(np.asarray(tr.step(ids, ids)._data))
    np.testing.assert_allclose(got, want, rtol=1e-5)
