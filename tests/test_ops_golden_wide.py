"""Wide table-driven golden op coverage (VERDICT r1 item 10: >= 60 ops
through the OpTest harness, eager + static executor legs, numeric-grad
oracle).  Priority order follows SURVEY §7.4 call-site counts.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import OpTest


def f32(shape, seed=0, lo=0.05, hi=1.0):
    def make():
        r = np.random.RandomState(seed)
        return (r.rand(*shape) * (hi - lo) + lo).astype(np.float32)
    return make


def sf32(shape, seed=0, scale=1.0):  # signed
    def make():
        return (np.random.RandomState(seed).randn(*shape)
                * scale).astype(np.float32)
    return make


def i64(shape, seed=0, hi=5):
    def make():
        return np.random.RandomState(seed).randint(
            0, hi, shape).astype(np.int64)
    return make


def boolean(shape, seed=0):
    def make():
        return np.random.RandomState(seed).rand(*shape) > 0.5
    return make


def case(name, op, ins, ref, wrt=(0,), attrs=None, static=True,
         out_rtol=1e-5, out_atol=1e-6, grad_rtol=1e-2, grad_atol=1e-2):
    return dict(name=name, op=op, ins=ins, ref=ref, wrt=wrt,
                attrs=attrs or {}, static=static, out_rtol=out_rtol,
                out_atol=out_atol, grad_rtol=grad_rtol, grad_atol=grad_atol)


_sp = lambda x: x * (1.0 / (1.0 + np.exp(-x)))  # silu ref

CASES = [
    # ---- unary float (output + grad) ----
    case("relu", F.relu, [sf32((3, 4), 1)], lambda x: np.maximum(x, 0)),
    case("tanh", paddle.tanh, [sf32((3, 4), 2)], np.tanh),
    case("sigmoid", paddle.sigmoid, [sf32((3, 4), 3)],
         lambda x: 1 / (1 + np.exp(-x))),
    case("exp", paddle.exp, [sf32((3, 4), 4)], np.exp),
    case("log", paddle.log, [f32((3, 4), 5, 0.2, 2.0)], np.log),
    case("sqrt", paddle.sqrt, [f32((3, 4), 6, 0.2, 2.0)], np.sqrt),
    case("rsqrt", paddle.rsqrt, [f32((3, 4), 7, 0.2, 2.0)],
         lambda x: 1 / np.sqrt(x)),
    case("abs", paddle.abs, [sf32((3, 4), 8)], np.abs),
    case("square", paddle.square, [sf32((3, 4), 9)], np.square),
    case("sin", paddle.sin, [sf32((3, 4), 10)], np.sin),
    case("cos", paddle.cos, [sf32((3, 4), 11)], np.cos),
    case("erf", paddle.erf, [sf32((3, 4), 12)],
         lambda x: np.vectorize(__import__("math").erf)(x).astype(
             np.float64)),
    case("log1p", paddle.log1p, [f32((3, 4), 13)], np.log1p),
    case("expm1", paddle.expm1, [sf32((3, 4), 14, 0.5)], np.expm1),
    case("reciprocal", paddle.reciprocal, [f32((3, 4), 15, 0.3, 2.0)],
         lambda x: 1 / x),
    case("atan", paddle.atan, [sf32((3, 4), 16)], np.arctan),
    case("sinh", paddle.sinh, [sf32((3, 4), 17, 0.5)], np.sinh),
    case("cosh", paddle.cosh, [sf32((3, 4), 18, 0.5)], np.cosh),
    case("silu", F.silu, [sf32((3, 4), 19)], _sp),
    case("leaky_relu", F.leaky_relu, [sf32((3, 4), 20)],
         lambda x: np.where(x > 0, x, 0.01 * x)),
    case("elu", F.elu, [sf32((3, 4), 21)],
         lambda x: np.where(x > 0, x, np.exp(np.minimum(x, 0)) - 1)),
    case("softplus", F.softplus, [sf32((3, 4), 22)],
         lambda x: np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0)),
    case("log_softmax", F.log_softmax, [sf32((3, 4), 23)],
         lambda x: x - x.max(-1, keepdims=True)
         - np.log(np.exp(x - x.max(-1, keepdims=True)).sum(
             -1, keepdims=True))),
    case("logsumexp", paddle.logsumexp, [sf32((3, 4), 24)],
         lambda x: np.log(np.exp(x - x.max()).sum()) + x.max()),
    # ---- binary (grads wrt both) ----
    case("subtract", paddle.subtract, [sf32((3, 4), 25), sf32((4,), 26)],
         lambda x, y: x - y, wrt=(0, 1)),
    case("divide", paddle.divide,
         [sf32((3, 4), 27), f32((3, 4), 28, 0.5, 2.0)],
         lambda x, y: x / y, wrt=(0, 1)),
    case("maximum", paddle.maximum, [sf32((3, 4), 29), sf32((3, 4), 30)],
         np.maximum, wrt=(0, 1)),
    case("minimum", paddle.minimum, [sf32((3, 4), 31), sf32((3, 4), 32)],
         np.minimum, wrt=(0, 1)),
    case("pow", paddle.pow, [f32((3, 4), 33, 0.3, 1.5)],
         lambda x: np.power(x, 2.5), attrs={"y": 2.5}),
    case("mod", paddle.mod, [f32((3, 4), 34, 1.0, 5.0),
                             f32((3, 4), 35, 1.0, 2.0)],
         lambda x, y: np.mod(x, y), wrt=()),
    case("floor_divide", paddle.floor_divide,
         [f32((3, 4), 36, 1.0, 9.0), f32((3, 4), 37, 1.0, 3.0)],
         lambda x, y: np.floor_divide(x, y), wrt=()),
    case("dot", paddle.dot, [sf32((5,), 38), sf32((5,), 39)],
         lambda x, y: np.dot(x, y), wrt=(0, 1)),
    case("bmm", paddle.bmm, [sf32((2, 3, 4), 40), sf32((2, 4, 5), 41)],
         lambda x, y: x @ y, wrt=(0, 1)),
    case("outer", paddle.outer, [sf32((3,), 42), sf32((4,), 43)],
         np.outer, wrt=(0, 1)),
    case("lerp", paddle.lerp,
         [sf32((3, 4), 44), sf32((3, 4), 45), f32((3, 4), 46)],
         lambda x, y, w: x + w * (y - x), wrt=(0, 1)),
    case("cross", paddle.cross, [sf32((4, 3), 47), sf32((4, 3), 48)],
         lambda x, y: np.cross(x, y), wrt=(0, 1)),
    case("addmm", paddle.addmm,
         [sf32((3, 5), 49), sf32((3, 4), 50), sf32((4, 5), 51)],
         lambda i, x, y: i + x @ y, wrt=(0, 1, 2)),
    # ---- reductions ----
    case("reduce_max", paddle.max, [sf32((3, 4), 52)],
         lambda x: x.max(), wrt=()),
    case("reduce_min", paddle.min, [sf32((3, 4), 53)],
         lambda x: x.min(), wrt=()),
    case("reduce_prod", paddle.prod, [f32((2, 3), 54, 0.5, 1.5)],
         lambda x: x.prod()),
    case("var", paddle.var, [sf32((3, 4), 55)],
         lambda x: x.var(ddof=1)),
    case("std", paddle.std, [sf32((3, 4), 56)],
         lambda x: x.std(ddof=1)),
    case("cumsum", paddle.cumsum, [sf32((3, 4), 57)],
         lambda x: x.reshape(-1).cumsum(), wrt=(0,)),
    case("cumprod", paddle.cumprod, [f32((3, 4), 58, 0.5, 1.5)],
         lambda x: x.cumprod(axis=1), attrs={"dim": 1}),
    case("amax_axis", paddle.amax, [sf32((3, 4), 59)],
         lambda x: x.max(axis=1), attrs={"axis": 1}, wrt=()),
    case("amin_axis", paddle.amin, [sf32((3, 4), 60)],
         lambda x: x.min(axis=1), attrs={"axis": 1}, wrt=()),
    # ---- shape / data movement (grad through) ----
    case("stack", lambda x, y: paddle.stack([x, y]),
         [sf32((3, 4), 61), sf32((3, 4), 62)],
         lambda x, y: np.stack([x, y]), wrt=(0, 1)),
    case("squeeze", paddle.squeeze, [sf32((3, 1, 4), 63)],
         lambda x: x.squeeze(1), attrs={"axis": 1}),
    case("unsqueeze", paddle.unsqueeze, [sf32((3, 4), 64)],
         lambda x: x[:, None, :], attrs={"axis": 1}),
    case("flatten", paddle.flatten, [sf32((2, 3, 4), 65)],
         lambda x: x.reshape(-1)),
    case("expand", paddle.expand, [sf32((1, 4), 66)],
         lambda x: np.broadcast_to(x, (3, 4)), attrs={"shape": [3, 4]}),
    case("tile", paddle.tile, [sf32((2, 3), 67)],
         lambda x: np.tile(x, (2, 2)), attrs={"repeat_times": [2, 2]}),
    case("flip", paddle.flip, [sf32((3, 4), 68)],
         lambda x: x[:, ::-1], attrs={"axis": 1}),
    case("roll", paddle.roll, [sf32((3, 4), 69)],
         lambda x: np.roll(x.reshape(-1), 2).reshape(3, 4),
         attrs={"shifts": 2}),
    case("tril", paddle.tril, [sf32((4, 4), 70)], np.tril),
    case("triu", paddle.triu, [sf32((4, 4), 71)], np.triu),
    case("trace", paddle.trace, [sf32((4, 4), 72)], np.trace),
    case("gather", paddle.gather, [sf32((6, 3), 73), i64((4,), 74, 6)],
         lambda x, i: x[i], wrt=(0,)),
    case("index_select", paddle.index_select,
         [sf32((6, 3), 75), i64((4,), 76, 6)],
         lambda x, i: x[i], wrt=(0,)),
    case("where", paddle.where,
         [boolean((3, 4), 77), sf32((3, 4), 78), sf32((3, 4), 79)],
         lambda c, x, y: np.where(c, x, y), wrt=(1, 2)),
    case("clip", paddle.clip, [sf32((3, 4), 80)],
         lambda x: np.clip(x, -0.5, 0.5),
         attrs={"min": -0.5, "max": 0.5}),
    # ---- comparison / logical / discrete (output only) ----
    case("argmax", paddle.argmax, [sf32((3, 4), 81)],
         lambda x: x.reshape(-1).argmax(), wrt=()),
    case("argmin", paddle.argmin, [sf32((3, 4), 82)],
         lambda x: x.reshape(-1).argmin(), wrt=()),
    case("equal", paddle.equal, [i64((3, 4), 83), i64((3, 4), 84)],
         lambda x, y: x == y, wrt=()),
    case("greater_than", paddle.greater_than,
         [sf32((3, 4), 85), sf32((3, 4), 86)],
         lambda x, y: x > y, wrt=()),
    case("less_than", paddle.less_than,
         [sf32((3, 4), 87), sf32((3, 4), 88)],
         lambda x, y: x < y, wrt=()),
    case("logical_and", paddle.logical_and,
         [boolean((3, 4), 89), boolean((3, 4), 90)],
         np.logical_and, wrt=()),
    case("logical_not", paddle.logical_not, [boolean((3, 4), 91)],
         np.logical_not, wrt=()),
    case("sign", paddle.sign, [sf32((3, 4), 92)], np.sign, wrt=()),
    case("floor", paddle.floor, [sf32((3, 4), 93, 3.0)], np.floor,
         wrt=()),
    case("ceil", paddle.ceil, [sf32((3, 4), 94, 3.0)], np.ceil, wrt=()),
    case("round", paddle.round, [sf32((3, 4), 95, 3.0)], np.round,
         wrt=()),
    case("one_hot", paddle.one_hot, [i64((5,), 96, 4)],
         lambda x: np.eye(4)[x], attrs={"num_classes": 4}, wrt=()),
    # ---- round-2 op families (loss/sequence/vision/framework) ----
    case("huber_loss", paddle.huber_loss,
         [sf32((3, 4), 201), sf32((3, 4), 202)],
         lambda x, y: np.where(np.abs(y - x) <= 1.0,
                               0.5 * np.square(y - x),
                               np.abs(y - x) - 0.5)),
    case("rank_loss", paddle.rank_loss,
         [f32((3, 1), 203, 0.0, 1.0), sf32((3, 1), 204),
          sf32((3, 1), 205)],
         lambda t, l, r: np.log1p(np.exp(l - r)) - t * (l - r),
         wrt=(1, 2)),
    case("modified_huber_loss", paddle.modified_huber_loss,
         [sf32((3, 4), 206), i64((3, 4), 207, 2)],
         lambda x, y: np.where((2 * y - 1) * x < -1, -4 * (2 * y - 1) * x,
                               np.where((2 * y - 1) * x < 1,
                                        np.square(1 - (2 * y - 1) * x),
                                        0.0)),
         wrt=()),
    case("squared_l2_norm", paddle.squared_l2_norm, [sf32((3, 4), 208)],
         lambda x: np.array([np.sum(x * x)])),
    case("l1_norm", paddle.l1_norm, [sf32((3, 4), 209)],
         lambda x: np.array([np.sum(np.abs(x))])),
    case("clip_by_norm", paddle.clip_by_norm, [sf32((3, 4), 210, 2.0)],
         lambda x: x * min(1.0, 1.0 / np.sqrt((x * x).sum())),
         attrs={"max_norm": 1.0}),
    case("cos_sim", paddle.cos_sim, [sf32((3, 4), 211), sf32((3, 4), 212)],
         lambda x, y: (np.sum(x * y, 1, keepdims=True)
                       / (np.linalg.norm(x, axis=1, keepdims=True)
                          * np.linalg.norm(y, axis=1, keepdims=True)))),
    case("squared_l2_distance", paddle.squared_l2_distance,
         [sf32((3, 4), 213), sf32((3, 4), 214)],
         lambda x, y: np.sum(np.square(x - y), axis=1)),
    case("affine_channel", paddle.affine_channel,
         [sf32((2, 3, 4, 4), 215), sf32((3,), 216), sf32((3,), 217)],
         lambda x, s, b: x * s.reshape(1, 3, 1, 1) + b.reshape(1, 3, 1, 1)),
    case("shuffle_channel", paddle.shuffle_channel,
         [sf32((1, 4, 2, 2), 218)],
         lambda x: x.reshape(1, 2, 2, 2, 2).transpose(0, 2, 1, 3, 4)
         .reshape(1, 4, 2, 2), attrs={"group": 2}),
    case("space_to_depth", paddle.space_to_depth,
         [sf32((1, 1, 4, 4), 219)],
         lambda x: x.reshape(1, 1, 2, 2, 2, 2).transpose(0, 3, 5, 1, 2, 4)
         .reshape(1, 4, 2, 2), attrs={"blocksize": 2}),
    case("pad_constant_like", paddle.pad_constant_like,
         [sf32((3, 4), 220), sf32((2, 3), 221)],
         lambda x, y: np.pad(y, [(0, 1), (0, 1)]), wrt=(1,)),
    case("fsp_matrix", paddle.fsp_matrix,
         [sf32((1, 2, 3, 3), 222), sf32((1, 4, 3, 3), 223)],
         lambda x, y: np.einsum("bihw,bjhw->bij", x, y) / 9.0),
    case("bilinear_tensor_product", paddle.bilinear_tensor_product,
         [sf32((2, 3), 224), sf32((2, 4), 225), sf32((5, 3, 4), 226)],
         lambda x, y, w: np.einsum("bi,kij,bj->bk", x, w, y)),
    case("conv_shift", paddle.conv_shift,
         [sf32((2, 5), 227), sf32((2, 3), 228)],
         lambda x, y: np.stack([
             np.array([sum(x[b, (j + k - 1) % 5] * y[b, k]
                           for k in range(3)) for j in range(5)])
             for b in range(2)])),
    case("row_conv", paddle.row_conv,
         [sf32((1, 4, 2), 229), sf32((2, 2), 230)],
         lambda x, w: np.stack([
             sum(np.pad(x[0], [(0, 1), (0, 0)])[t + j] * w[j]
                 for j in range(2)) for t in range(4)])[None]),
    case("add_position_encoding", paddle.add_position_encoding,
         [sf32((1, 3, 4), 231)],
         lambda x: x + np.concatenate([
             np.sin(np.arange(3)[:, None]
                    / np.power(10000.0, np.arange(2) / 2)),
             np.cos(np.arange(3)[:, None]
                    / np.power(10000.0, np.arange(2) / 2))], axis=1)[None],
         out_rtol=1e-4, out_atol=1e-5),
    case("sequence_softmax", paddle.sequence_softmax,
         [sf32((2, 3), 232),
          lambda: np.array([3, 2], np.int64)],
         lambda x, l: np.stack([
             np.concatenate([
                 np.exp(x[i, :l[i]]) / np.exp(x[i, :l[i]]).sum(),
                 np.zeros(3 - l[i], np.float32)])
             for i in range(2)]),
         wrt=(0,), out_rtol=1e-4, out_atol=1e-5),
    # static=False: num_segments derives from the ids VALUES (a
    # data-dependent shape), so segment_pool is an eager/boundary op
    case("segment_sum", paddle.segment_sum,
         [sf32((4, 2), 233), lambda: np.array([0, 0, 1, 1], np.int32)],
         lambda x, ids: np.stack([x[:2].sum(0), x[2:].sum(0)]),
         wrt=(0,), static=False),
    case("size", paddle.size, [sf32((3, 4), 236)],
         lambda x: np.array(12, np.int64), wrt=()),
    case("memcpy", paddle.memcpy, [sf32((3, 4), 237)], lambda x: x),
    case("softmax_mask_fuse_ut", paddle.softmax_mask_fuse_upper_triangle,
         [sf32((1, 1, 3, 3), 239)],
         lambda x: np.array([[[
             np.concatenate([np.exp(x[0, 0, i, :i + 1])
                             / np.exp(x[0, 0, i, :i + 1]).sum(),
                             np.zeros(2 - i, np.float32)])
             for i in range(3)]]]),
         out_rtol=1e-4, out_atol=1e-5),
    case("cast", paddle.cast, [sf32((3, 4), 97)],
         lambda x: x.astype(np.float64), attrs={"dtype": "float64"},
         wrt=()),
    case("sort", paddle.sort, [sf32((3, 4), 98)],
         lambda x: np.sort(x, axis=-1), wrt=()),
]


def _make_optest(c):
    class _T(OpTest):
        op = staticmethod(c["op"])
        attrs = c["attrs"]
        out_rtol = c["out_rtol"]
        out_atol = c["out_atol"]
        grad_rtol = c["grad_rtol"]
        grad_atol = c["grad_atol"]

        def make_inputs(self):
            return [m() for m in c["ins"]]

        def ref(self, *arrays):
            return c["ref"](*arrays)

        def check_output_static(self, arrays=None, refs=None):
            if not c["static"]:
                return
            super().check_output_static(arrays, refs)

    _T.__name__ = f"Golden_{c['name']}"
    return _T()


@pytest.mark.parametrize("c", CASES, ids=[c["name"] for c in CASES])
def test_golden_wide(c):
    t = _make_optest(c)
    t.check_output()
    if c["wrt"]:
        t.check_grad(wrt=c["wrt"])


def test_topk_multi_output():
    x = np.random.RandomState(99).randn(3, 6).astype(np.float32)
    vals, idx = paddle.topk(paddle.to_tensor(x), k=2)
    np.testing.assert_allclose(
        vals.numpy(), np.sort(x, axis=-1)[:, ::-1][:, :2], rtol=1e-6)
    ref_idx = np.argsort(-x, axis=-1)[:, :2]
    np.testing.assert_array_equal(idx.numpy(), ref_idx)


def test_split_and_chunk_grads_flow():
    x = paddle.to_tensor(
        np.random.RandomState(100).randn(4, 6).astype(np.float32),
        stop_gradient=False)
    parts = paddle.split(x, 3, axis=1)
    assert len(parts) == 3 and tuple(parts[0].shape) == (4, 2)
    loss = paddle.sum(paddle.multiply(parts[0], parts[0]))
    loss.backward()
    g = x.grad.numpy()
    np.testing.assert_allclose(g[:, :2], 2 * x.numpy()[:, :2], rtol=1e-5)
    np.testing.assert_allclose(g[:, 2:], np.zeros((4, 4)), atol=1e-7)


def test_coverage_counts_sixty_ops():
    """The golden surface (this file + test_ops_golden.py classes) must
    cover >= 60 distinct ops."""
    import test_ops_golden as g1

    classic = [n for n in dir(g1) if n.startswith("Test")]
    assert len(CASES) + len(classic) + 2 >= 60, (
        len(CASES), len(classic))
