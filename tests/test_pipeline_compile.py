"""Compiled pipeline parallelism vs single-device eager (dist-test contract:
pipelined losses must match non-pipelined losses step-by-step, SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny
from paddle_tpu.parallel.env import build_mesh
from paddle_tpu.parallel.pipeline_compile import (
    GPTPipeAdapter, PipelinedTrainStep,
)


def _setup(seed=0, B=8, L=16):
    paddle.seed(seed)
    cfg = gpt_tiny()
    cfg.dropout = 0.0
    cfg.num_layers = 4
    model = GPTForPretraining(cfg)
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (B, L)).astype(np.int32)
    labels = rng.randint(0, cfg.vocab_size, (B, L)).astype(np.int32)
    return cfg, model, ids, labels


def _eager_losses(n_steps=3):
    cfg, model, ids, labels = _setup()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    out = []
    ti, tl = paddle.to_tensor(ids), paddle.to_tensor(labels)
    for _ in range(n_steps):
        loss = model.loss(ti, tl)
        loss.backward()
        opt.step()
        opt.clear_grad()
        out.append(float(loss.numpy()))
    return out


def _pipelined_losses(mesh_dims, num_micro, n_steps=3, amp=None):
    cfg, model, ids, labels = _setup()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    mesh = build_mesh(mesh_dims)
    tr = PipelinedTrainStep(GPTPipeAdapter(model), opt, mesh,
                            num_micro=num_micro, amp_dtype=amp, remat=True)
    return [
        float(np.asarray(tr.step(ids, labels)._data))
        for _ in range(n_steps)
    ]


def test_pp_matches_single_device():
    ref = _eager_losses()
    pp = _pipelined_losses({"pipe": 4}, num_micro=2)
    np.testing.assert_allclose(pp, ref, rtol=2e-4, atol=2e-4)


def test_pp_dp_matches_single_device():
    ref = _eager_losses()
    pp = _pipelined_losses({"pipe": 2, "data": 2}, num_micro=4)
    np.testing.assert_allclose(pp, ref, rtol=2e-4, atol=2e-4)


def test_pp_tp_matches_single_device():
    ref = _eager_losses()
    pp = _pipelined_losses({"pipe": 2, "model": 2}, num_micro=2)
    np.testing.assert_allclose(pp, ref, rtol=2e-4, atol=2e-4)


def test_pp_state_dict_roundtrip():
    cfg, model, ids, labels = _setup()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    mesh = build_mesh({"pipe": 4})
    tr = PipelinedTrainStep(GPTPipeAdapter(model), opt, mesh, num_micro=2)
    tr.step(ids, labels)
    sd = tr.state_dict()
    # a fresh model loaded from the trained state reproduces the loss
    paddle.seed(123)
    model2 = GPTForPretraining(cfg)
    model2.set_state_dict(sd)
    l2 = float(model2.loss(paddle.to_tensor(ids),
                           paddle.to_tensor(labels)).numpy())
    tr2 = PipelinedTrainStep(GPTPipeAdapter(model2), opt, mesh, num_micro=2)
    l3 = float(np.asarray(tr2.step(ids, labels)._data))
    np.testing.assert_allclose(l3, l2, rtol=2e-4, atol=2e-4)


def test_pp_embed_head_cond_gated():
    """VERDICT r1 weak-5: embed/head must be lax.cond-gated per stage, not
    computed everywhere and discarded via jnp.where.  Structural check: the
    pipeline tick's scan body carries cond primitives."""
    paddle.seed(0)
    cfg = gpt_tiny()
    cfg.dropout = 0.0
    model = GPTForPretraining(cfg)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    mesh = build_mesh({"pipe": 2, "data": 2})
    tr = PipelinedTrainStep(GPTPipeAdapter(model), opt, mesh, num_micro=4)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
    lbl = rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
    tr.step(paddle.to_tensor(ids), paddle.to_tensor(lbl))

    def subjaxprs(v):
        if hasattr(v, "eqns"):  # raw Jaxpr (e.g. shard_map param)
            yield v
        elif hasattr(v, "jaxpr"):  # ClosedJaxpr (e.g. pjit param)
            yield v.jaxpr
        elif isinstance(v, (list, tuple)):
            for b in v:
                yield from subjaxprs(b)

    def count_conds(jaxpr):
        n = 0
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "cond":
                n += 1
            for v in eqn.params.values():
                for j in subjaxprs(v):
                    n += count_conds(j)
        return n

    import jax as _jax

    traced = _jax.make_jaxpr(
        lambda *a: tr._jit_step(*a))(
        tr.other_params, tr.block_params, tr._opt_state["other"],
        tr._opt_state["block"], ids, lbl, _jax.random.PRNGKey(0),
        np.uint32(0), np.float32(0.1))
    assert count_conds(traced.jaxpr) >= 2  # embed gate + head gate


def test_pp_opt_state_zero_sharded():
    """VERDICT r1 item 3: pipeline opt state must range-shard over 'data'
    (was replicated P()), and block state must vary over 'pipe'."""
    paddle.seed(0)
    cfg = gpt_tiny()
    cfg.dropout = 0.0
    model = GPTForPretraining(cfg)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    mesh = build_mesh({"pipe": 2, "data": 2})
    tr = PipelinedTrainStep(GPTPipeAdapter(model), opt, mesh, num_micro=4)
    assert "data" in tr._buf_axes["other"]
    assert set(tr._buf_axes["block"]) >= {"pipe", "data"}
    for group in ("other", "block"):
        for k, v in tr._opt_state[group].items():
            if v.ndim:
                # one local block per (buf-axes) rank combination
                assert v.shape[:-1] == tuple(
                    mesh.shape[a] for a in tr._buf_axes[group])
                for shard in v.addressable_shards:
                    assert all(s == 1 for s in shard.data.shape[:-1])
    # and it still trains
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
    lbl = rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
    l0 = float(np.asarray(tr.step(paddle.to_tensor(ids),
                                  paddle.to_tensor(lbl))._data))
    for _ in range(3):
        l1 = float(np.asarray(tr.step(paddle.to_tensor(ids),
                                      paddle.to_tensor(lbl))._data))
    assert l1 < l0


def test_remat_bounds_pipeline_activation_memory():
    """VERDICT r2 #3 (measured honesty): the docstring claims per-block
    remat provides the 1F1B-class activation-memory bound compiler-side.
    Assert it: remat=True compiles to a strictly smaller temp (activation
    + workspace) footprint than remat=False at identical loss.  Full
    numbers: tools/pipeline_tradeoff.py -> docs/PERF.md."""
    rng = np.random.RandomState(0)
    cfg = gpt_tiny()
    cfg.num_layers = 4
    ids = rng.randint(0, cfg.vocab_size, (8, 32)).astype(np.int32)
    lbl = rng.randint(0, cfg.vocab_size, (8, 32)).astype(np.int32)

    stats = {}
    for remat in (False, True):
        paddle.seed(0)
        model = GPTForPretraining(cfg)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        mesh = build_mesh({"pipe": 2, "data": 2})
        tr = PipelinedTrainStep(GPTPipeAdapter(model), opt, mesh,
                                num_micro=4, remat=remat)
        ma = tr.memory_analysis(ids, lbl)
        if ma is None:
            pytest.skip("backend reports no memory analysis")
        loss = float(np.asarray(tr.step(paddle.to_tensor(ids),
                                        paddle.to_tensor(lbl))._data))
        stats[remat] = (ma.temp_size_in_bytes, loss)

    assert stats[True][0] < stats[False][0], stats
    np.testing.assert_allclose(stats[True][1], stats[False][1],
                               rtol=1e-5)
