"""Hybrid parallelism composition tests on larger virtual meshes.

BASELINE config 4/5 stand-ins that CI can actually run: compose
dp x tp x sp (ZeRO-3) with 8 devices in one step, dp x ep MoE in another,
and assert loss parity against single-device eager — the virtual-mesh
analogue of the reference's multi-process `check_with_place` contract
(test_dist_base.py:1266).
"""
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny
from paddle_tpu.parallel.env import build_mesh
from paddle_tpu.parallel.hybrid import CompiledTrainStep


def _np(t):
    return np.asarray(t._data)


def _loss_parity(model, trainer, ids, rtol=2e-3):
    t_ids = paddle.to_tensor(ids)
    with paddle.no_grad():
        eager = float(_np(model.loss(t_ids, t_ids)))
    l1 = float(_np(trainer.step(t_ids, t_ids)))
    np.testing.assert_allclose(l1, eager, rtol=rtol)
    l2 = float(_np(trainer.step(t_ids, t_ids)))
    assert np.isfinite(l2) and l2 < l1
    return l1, l2


def test_dp_tp_sp_zero3_8dev_parity():
    """The dryrun's primary mesh as a CI assertion: data2 x model2 x seq2
    with ZeRO-3 must reproduce the single-device loss."""
    paddle.seed(10)
    cfg = gpt_tiny()
    cfg.dropout = 0.0
    model = GPTForPretraining(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    mesh = build_mesh({"data": 2, "model": 2, "seq": 2})
    tr = CompiledTrainStep(model, lambda m, i, l: m.loss(i, l), opt, mesh,
                           zero_stage=3)
    rng = np.random.RandomState(10)
    ids = rng.randint(0, cfg.vocab_size, (4, 32)).astype(np.int32)
    _loss_parity(model, tr, ids)


def test_dp_tp_ep_moe_parity():
    """MoE composed with tensor parallelism for the dense parts:
    data2 x model2 x expert2 on 8 devices."""
    paddle.seed(11)
    cfg = gpt_tiny()
    cfg.dropout = 0.0
    cfg.num_experts = 4
    model = GPTForPretraining(cfg)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    mesh = build_mesh({"data": 2, "model": 2, "expert": 2})
    tr = CompiledTrainStep(model, lambda m, i, l: m.loss(i, l), opt, mesh)
    rng = np.random.RandomState(11)
    ids = rng.randint(0, cfg.vocab_size, (4, 16)).astype(np.int32)
    _loss_parity(model, tr, ids)
