"""End-to-end smoke tests: core autograd + LeNet training slice."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def test_matmul_grad():
    x = paddle.to_tensor(np.random.rand(4, 3).astype("float32"),
                         stop_gradient=False)
    w = paddle.to_tensor(np.random.rand(3, 2).astype("float32"),
                         stop_gradient=False)
    y = paddle.matmul(x, w)
    loss = paddle.mean(y)
    loss.backward()
    assert x.grad.shape == [4, 3]
    assert w.grad.shape == [3, 2]
    # d(mean(x@w))/dw = x^T @ ones/8
    expect = x.numpy().T @ np.full((4, 2), 1 / 8.0, np.float32)
    np.testing.assert_allclose(w.grad.numpy(), expect, rtol=1e-5)


def test_lenet_training_loss_decreases():
    paddle.seed(0)
    from paddle_tpu.vision.models import LeNet

    net = LeNet()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    img = paddle.to_tensor(np.random.rand(8, 1, 28, 28).astype("float32"))
    lbl = paddle.to_tensor(np.random.randint(0, 10, (8, 1)))
    losses = []
    for _ in range(5):
        out = net(img)
        loss = paddle.mean(F.softmax_with_cross_entropy(out, lbl))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]
