"""Real multi-process distributed tests (VERDICT r1 item 7).

The reference's dist-test contract is multi-process-localhost
(test_dist_base.py check_with_place:1266): fork trainer processes, pipe out
losses, assert dist losses == single-process losses step-by-step.  These
tests exercise distributed/launch.py, distributed/spawn.py and
fleet/elastic.py as real process managers, with jax.distributed over
localhost CPU as the comm backend.
"""
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAINER = os.path.join(REPO, "tests", "dist_dp_trainer.py")

from dist_capability import (SKIP_REASON,  # noqa: E402 (probe helper)
                             multiprocess_collectives_available)

# the DP-loss tests need REAL cross-process collectives, which the CPU
# backend cannot execute (the pre-existing tier-1 red since the seed);
# the capability is PROBED, not assumed, so multi-host TPU/GPU runs
# keep full coverage (dist_capability.py)
needs_collectives = pytest.mark.skipif(
    not multiprocess_collectives_available(), reason=SKIP_REASON)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _single_process_losses(tmp_path):
    out = str(tmp_path / "single.json")
    env = dict(os.environ)
    env.update({"PADDLE_TRAINER_ID": "0", "PADDLE_TRAINERS_NUM": "1"})
    subprocess.run([sys.executable, TRAINER, out], env=env, check=True,
                   cwd=REPO, capture_output=True, timeout=300)
    with open(out) as f:
        return json.load(f)


@needs_collectives
def test_launch_two_process_dp_matches_single(tmp_path):
    """distributed/launch.py forks one worker per node rank; 2-process DP
    losses must match the single-process run (check_with_place)."""
    from paddle_tpu.distributed.launch import (
        launch_workers, watch_local_trainers,
    )

    master = f"127.0.0.1:{_free_port()}"
    out = str(tmp_path / "dist.json")
    procs = []
    for rank in range(2):
        procs += launch_workers(TRAINER, [out] if rank == 0 else ["-"],
                                nnodes=2, node_rank=rank,
                                master_endpoint=master)
    deadline = time.time() + 300
    alive = procs
    while alive and time.time() < deadline:
        alive = watch_local_trainers(alive, 2)
        time.sleep(0.5)
    assert not alive, "trainers did not finish in time"
    with open(out) as f:
        dist_losses = json.load(f)
    ref = _single_process_losses(tmp_path)
    np.testing.assert_allclose(dist_losses, ref, rtol=1e-6, atol=1e-7)


def test_launch_watchdog_aborts_all_on_failure(tmp_path):
    """watch_local_trainers must kill surviving ranks when one dies
    (distributed/utils.py watchdog contract)."""
    from paddle_tpu.distributed.launch import (
        TrainerProc, watch_local_trainers,
    )

    ok = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(60)"])
    bad = subprocess.Popen([sys.executable, "-c", "import sys; sys.exit(3)"])
    bad.wait()
    procs = [TrainerProc(ok, 0), TrainerProc(bad, 1)]
    with pytest.raises(RuntimeError, match="rank 1 failed"):
        watch_local_trainers(procs, 2)
    ok.wait(timeout=10)
    assert ok.poll() is not None  # survivor was terminated


@needs_collectives
def test_spawn_two_process_dp_matches_single(tmp_path):
    """paddle.distributed.spawn forks fresh interpreters per rank."""
    from paddle_tpu.distributed.spawn import spawn

    sys.path.insert(0, os.path.join(REPO, "tests"))
    from dist_dp_trainer import spawn_entry

    master = f"127.0.0.1:{_free_port()}"
    old = {k: os.environ.get(k)
           for k in ("PADDLE_MASTER", "PADDLE_TRAINERS_NUM",
                     "PADDLE_TRAINER_ID")}
    os.environ["PADDLE_MASTER"] = master
    os.environ["PADDLE_TRAINERS_NUM"] = "2"
    os.environ.pop("PADDLE_TRAINER_ID", None)
    try:
        spawn(spawn_entry, args=(str(tmp_path),), nprocs=2, join=True)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    with open(tmp_path / "spawn_losses.json") as f:
        dist_losses = json.load(f)
    ref = _single_process_losses(tmp_path)
    np.testing.assert_allclose(dist_losses, ref, rtol=1e-6, atol=1e-7)


# ---- elastic (mocked-store contract, test_fleet_elastic_manager.py) ----

def test_elastic_membership_and_restart_on_scale_change():
    from paddle_tpu.distributed.fleet.elastic import (
        ElasticManager, ElasticStatus, MemoryStore,
    )

    store = MemoryStore()
    m1 = ElasticManager(store=store, np=2, host="10.0.0.1", job_id="j1")
    m2 = ElasticManager(store=store, np=2, host="10.0.0.2", job_id="j1")
    m1.register()
    assert not m1._match()
    m2.register()
    assert m1.wait(timeout=5)
    assert m1.hosts() == ["10.0.0.1", "10.0.0.2"]

    # launcher supervises a real local process to completion
    m1.launcher.launch([sys.executable, "-c", "print('ok')"])
    deadline = time.time() + 30
    status = ElasticStatus.HOLD
    while status == ElasticStatus.HOLD and time.time() < deadline:
        status = m1.launcher.watch()
        time.sleep(0.2)
    assert status == ElasticStatus.COMPLETED

    # membership change triggers RESTART: member 2 leaves
    m1.launcher.launch([sys.executable, "-c", "import time; time.sleep(60)"])
    m2.exit()
    assert m1.watch() == ElasticStatus.RESTART
    assert m1.launcher.procs == []  # trainers were torn down
    m1.exit()
