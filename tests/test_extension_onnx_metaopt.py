"""Custom-op extension, onnx export, and new meta-optimizers (dgc /
fp16_allreduce / asp) tests.

Ref: custom-op tests (custom_op/test_custom_relu_op_setup.py style: build a
C op, compare against native), fleet meta-optimizer rewrite assertions
(SURVEY §4.4: check the op list of the rewritten program).
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.utils import cpp_extension


def test_register_custom_op_with_custom_grad():
    import jax.numpy as jnp

    def fwd(x):
        return jnp.square(x)

    def bwd(g, x):
        return (g * 3.0 * x,)  # deliberately not the true grad (2x)

    op = cpp_extension.register_custom_op("my_square", fwd, backward=bwd)
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                         stop_gradient=False)
    y = op(x)
    np.testing.assert_allclose(y.numpy(), [1.0, 4.0])
    loss = paddle.sum(y)
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 6.0])  # custom vjp used


def test_load_c_extension(tmp_path):
    src = tmp_path / "my_ops.cc"
    src.write_text(r"""
extern "C" void cube_forward(const float* in, float* out, long long n) {
    for (long long i = 0; i < n; ++i) out[i] = in[i] * in[i] * in[i];
}
extern "C" void cube_backward(const float* in, float* out, long long n) {
    for (long long i = 0; i < n; ++i) out[i] = 3.0f * in[i] * in[i];
}
""")
    mod = cpp_extension.load("myext", [str(src)],
                             build_directory=str(tmp_path / "build"))
    op = mod.register("cube_forward", backward_symbol="cube_backward")
    x = paddle.to_tensor(np.array([1.0, 2.0, -2.0], np.float32),
                         stop_gradient=False)
    y = op(x)
    np.testing.assert_allclose(y.numpy(), [1.0, 8.0, -8.0])
    paddle.sum(y).backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 12.0, 12.0])


def test_onnx_export_writes_stablehlo(tmp_path):
    from paddle_tpu.static import InputSpec

    net = paddle.nn.Linear(4, 2)
    prefix = paddle.onnx.export(
        net, str(tmp_path / "lin.onnx"),
        input_spec=[InputSpec([2, 4], "float32")])
    assert os.path.exists(prefix + ".pdexported")


def _build_sgd_program():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data(name="x", shape=[4, 8], dtype="float32")
        y = static.nn.fc(x, size=2)
        from paddle_tpu.static.nn_static import mean

        loss = mean(y * y)
    return main, startup, loss


def _fleet_minimize(strategy_flags, loss):
    from paddle_tpu.distributed.fleet.distributed_strategy import (
        DistributedStrategy,
    )
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        apply_meta_optimizers,
    )
    from paddle_tpu.distributed.fleet import Fleet

    strategy = DistributedStrategy()
    for k, v in strategy_flags.items():
        setattr(strategy, k, v)
    f = Fleet()
    f.init(is_collective=True, strategy=strategy)
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
    return apply_meta_optimizers(opt, strategy, loss, None, f)


def test_dgc_rewrite_inserts_ops():
    paddle.enable_static()
    try:
        main, startup, loss = _build_sgd_program()
        with static.program_guard(main, startup):
            _fleet_minimize({"dgc": True}, loss)
        types = [op.type for op in main.global_block().ops]
        assert "dgc" in types
        # residual var materialized + persistable
        res_vars = [n for n in main.global_block().vars
                    if n.endswith("@DGC_RESIDUAL")]
        assert res_vars
        assert all(main.global_block().vars[n].persistable for n in res_vars)
        # program still runs and updates params
        exe = static.Executor()
        exe.run(startup)
        xv = np.random.RandomState(0).randn(4, 8).astype("float32")
        l0 = exe.run(main, feed={"x": xv}, fetch_list=[loss])[0]
        for _ in range(5):
            l1 = exe.run(main, feed={"x": xv}, fetch_list=[loss])[0]
        assert float(l1) < float(l0)
    finally:
        paddle.disable_static()


def test_fp16_allreduce_rewrite():
    paddle.enable_static()
    try:
        main, startup, loss = _build_sgd_program()
        with static.program_guard(main, startup):
            _fleet_minimize({"fp16_allreduce": True}, loss)
        types = [op.type for op in main.global_block().ops]
        assert "c_allreduce_sum_fp16" in types
        exe = static.Executor()
        exe.run(startup)
        xv = np.random.RandomState(0).randn(4, 8).astype("float32")
        l0 = exe.run(main, feed={"x": xv}, fetch_list=[loss])[0]
        for _ in range(5):
            l1 = exe.run(main, feed={"x": xv}, fetch_list=[loss])[0]
        assert float(l1) < float(l0)
    finally:
        paddle.disable_static()


def test_op_bench_harness_runs():
    import subprocess
    import sys
    import json

    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "tools", "op_bench.py"),
         "--op", "elementwise_add", "--shape", "64x64,64x64",
         "--repeat", "3"],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["op"] == "elementwise_add" and rec["eager_us"] > 0
